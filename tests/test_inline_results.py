"""Edge cases of small-result inlining (the submission fast path's result
plane): threshold-exact values inline, over-threshold values go to the shm
store, an inlined result later borrowed cross-process is PROMOTED to the
shm store (with the standard free fan-out), retries under chaos frame
drops replay the same inlined bytes exactly once, and streaming-generator
yields bypass the result-inlining knob unchanged.
"""

import json
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.core.object_store import PlasmaRecord
from ray_tpu.core.rpc import run_async
from ray_tpu.utils.testing import CPU_WORKER_ENV


def _record_of(ref):
    from ray_tpu.core.core_worker import global_worker
    return global_worker().memory_store.get_if_exists(ref.id)


def _flat_size(value) -> int:
    return serialization.serialize(value).flat_size()


# ------------------------------------------------------------- threshold

def test_result_exactly_at_threshold_inlines():
    """A result whose serialized size is EXACTLY inline_result_max_bytes
    still inlines (<=, not <); one byte past it goes to the shm store."""
    at = b"y" * 150_000
    over = b"y" * 150_001
    threshold = _flat_size(at)
    assert _flat_size(over) == threshold + 1
    ray_tpu.init(num_cpus=2, worker_env=dict(CPU_WORKER_ENV),
                 _system_config={"inline_result_max_bytes": threshold})
    try:
        @ray_tpu.remote
        def make(n):
            return b"y" * n

        ref_at = make.remote(len(at))
        assert ray_tpu.get(ref_at, timeout=60) == at
        rec = _record_of(ref_at)
        assert isinstance(rec, (bytes, bytearray)), \
            f"at-threshold result was not inlined: {type(rec)}"

        ref_over = make.remote(len(over))
        assert ray_tpu.get(ref_over, timeout=60) == over
        assert isinstance(_record_of(ref_over), PlasmaRecord), \
            "over-threshold result did not spill to the shm store"
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------- promotion

def test_inlined_result_promotes_on_cross_process_borrow():
    """An inlined result above the direct-call size that a borrower pulls
    cross-process must be promoted to the shm store — the owner's record
    becomes a PlasmaRecord, the borrower reads the right bytes, and the
    standard refcount free reclaims the shm copy."""
    ray_tpu.init(num_cpus=2, worker_env=dict(CPU_WORKER_ENV),
                 _system_config={"inline_result_max_bytes": 400_000})
    try:
        from ray_tpu.core.core_worker import global_worker
        w = global_worker()

        def stats():
            return run_async(w.agent.call("store_stats"))

        base_objects = stats()["num_objects"]

        @ray_tpu.remote
        def produce():
            return np.arange(30_000, dtype=np.float64)  # ~240 KB, inlined

        ref = produce.remote()
        out = ray_tpu.get(ref, timeout=60)
        assert isinstance(_record_of(ref), (bytes, bytearray)), \
            "result above max_direct_call_object_size was not inlined " \
            "under the raised inline_result_max_bytes"

        @ray_tpu.remote
        class Borrower:
            def grab(self, boxed):
                v = ray_tpu.get(boxed[0])
                return float(v.sum())

        b = Borrower.remote()
        got = ray_tpu.get(b.grab.remote([ref]), timeout=60)
        assert got == float(out.sum())
        rec = _record_of(ref)
        assert isinstance(rec, PlasmaRecord), \
            f"borrowed inlined result was not promoted: {type(rec)}"
        assert stats()["num_objects"] >= base_objects + 1

        # the promoted copy frees through the normal refcount fan-out
        ray_tpu.kill(b)
        del ref, rec
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if stats()["num_objects"] <= base_objects:
                break
            time.sleep(0.1)
        else:
            pytest.fail(f"promoted result never freed: {stats()}")
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------ chaos retry

@pytest.mark.chaos
def test_retried_inlined_actor_result_exactly_once():
    """A dropped actor_task reply replays the COMMITTED inlined result from
    the worker's dedup window: the method runs exactly once and the caller
    sees the same inlined bytes the first execution produced."""
    spec = {"seed": 3, "rules": [
        {"kind": "drop_reply", "prob": 1.0, "method": "actor_task",
         "times": 1}]}
    spec_json = json.dumps(spec)
    os.environ["RAYTPU_CHAOS_SPEC"] = spec_json
    try:
        ray_tpu.init(num_cpus=2, worker_env=dict(CPU_WORKER_ENV),
                     _system_config={"chaos_spec": spec_json})

        @ray_tpu.remote
        class Bump:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                # payload varies per EXECUTION: a re-run would change it,
                # so equality below proves replay-not-reexecute
                return (self.n, os.urandom(20_000))

        a = Bump.remote()
        n1, blob1 = ray_tpu.get(a.bump.remote(), timeout=120)
        assert n1 == 1, "dropped reply re-executed the method"
        assert len(blob1) == 20_000
        n2, _ = ray_tpu.get(a.bump.remote(), timeout=120)
        assert n2 == 2, f"method ran {n2 - 1} times for the second call"
    finally:
        os.environ.pop("RAYTPU_CHAOS_SPEC", None)
        ray_tpu.shutdown()


@pytest.mark.chaos
def test_task_inline_result_survives_push_frame_drop():
    """A dropped push_task frame fails the lease's worker; the retry
    re-executes the (stateless) task and the caller still gets the exact
    inlined bytes.  (Client-side drop_request: the driver's injector fires
    exactly once — a server-side drop_reply would re-fire in every freshly
    spawned worker's injector and exhaust any retry budget.)"""
    spec = {"seed": 5, "rules": [
        {"kind": "drop_request", "prob": 1.0, "method": "push_task",
         "times": 1}]}
    spec_json = json.dumps(spec)
    os.environ["RAYTPU_CHAOS_SPEC"] = spec_json
    try:
        ray_tpu.init(num_cpus=2, worker_env=dict(CPU_WORKER_ENV),
                     _system_config={"chaos_spec": spec_json})

        @ray_tpu.remote(max_retries=3)
        def blob():
            return b"z" * 30_000

        assert ray_tpu.get(blob.remote(), timeout=120) == b"z" * 30_000
    finally:
        os.environ.pop("RAYTPU_CHAOS_SPEC", None)
        ray_tpu.shutdown()


# -------------------------------------------------------------- generators

def test_generator_yields_bypass_result_inlining():
    """Streaming yields are governed by max_direct_call_object_size, NOT by
    inline_result_max_bytes: a huge result-inline threshold must not pull
    multi-hundred-KB yields out of the shm store (the streaming pipeline
    is unchanged by the fast path)."""
    ray_tpu.init(num_cpus=2, worker_env=dict(CPU_WORKER_ENV),
                 _system_config={"inline_result_max_bytes": 10 << 20})
    try:
        @ray_tpu.remote(num_returns="streaming")
        def gen():
            for i in range(3):
                yield np.full(50_000, i, dtype=np.float64)  # ~400 KB

        out_refs = list(gen.remote())
        assert len(out_refs) == 3
        for i, r in enumerate(out_refs):
            rec = _record_of(r)
            assert isinstance(rec, PlasmaRecord), \
                f"yield {i} was inlined ({type(rec)}) — generator returns " \
                "must bypass inline_result_max_bytes"
            v = ray_tpu.get(r, timeout=60)
            assert float(v[0]) == float(i) and v.shape == (50_000,)
    finally:
        ray_tpu.shutdown()
