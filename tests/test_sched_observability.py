"""Scheduler explain plane + control-plane saturation observability.

A wedged workload — infeasible resource ask, backpressured node, draining
node, gate-parked burst — must be diagnosable end to end from
``raytpu explain`` / ``state.summarize_tasks()["pending_reasons"]``
output alone; and the saturation half (loop busy fractions, per-GCS-
handler busy seconds, backpressure counters) must appear when
``sched_metrics_enabled`` is on and add ZERO series when it is off.

Reference: the Ray paper's debuggability-as-first-class bet (1712.05889)
and Podracer's provably-cheap control plane (2104.06272).
"""

import argparse
import asyncio
import threading
import time

import pytest

import ray_tpu
from ray_tpu.core import sched_explain
from ray_tpu.core.config import Config, reset_config, set_config
from ray_tpu.core.rpc import RpcClient, RpcServer, run_async
from ray_tpu.core.sched_explain import PendingReason
from ray_tpu.core.scheduling import NodeView, pack_bundles, pick_node
from ray_tpu.util.metrics import snapshot_registry


def _wait(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    assert cond(), f"timed out waiting for {msg}"


# ------------------------------------------------------------------ units

def _view():
    return {
        "alive": NodeView("alive", "h:1", {"CPU": 2}, {"CPU": 2}),
        "drainy": NodeView("drainy", "h:2", {"CPU": 2}, {"CPU": 2},
                           draining=True),
        "deady": NodeView("deady", "h:3", {"CPU": 2}, {"CPU": 2},
                          alive=False),
        "tiny": NodeView("tiny", "h:4", {"CPU": 0.5}, {"CPU": 0.5}),
    }


def test_pick_node_explain_rejection_causes():
    ex = {}
    nid = pick_node(_view(), {"CPU": 1}, explain=ex)
    assert nid == "alive" and ex["chosen"] == "alive"
    assert ex["candidates"] == 4
    assert ex["rejected"] == {"drainy": "draining", "deady": "dead",
                              "tiny": "resources"}

    # hard affinity to a draining node: an affinity miss, typed as such
    from ray_tpu.core.common import NodeAffinitySchedulingStrategy
    ex = {}
    nid = pick_node(_view(), {"CPU": 1},
                    NodeAffinitySchedulingStrategy("drainy", soft=False),
                    explain=ex)
    assert nid is None and ex["chosen"] is None
    assert ex["rejected"]["drainy"] == "draining"

    # the None-explain path still works (and pays nothing)
    assert pick_node(_view(), {"CPU": 1}) == "alive"


def test_pack_bundles_explain():
    ex = {}
    placement = pack_bundles(_view(), [{"CPU": 1}, {"CPU": 1}],
                             "STRICT_SPREAD", explain=ex)
    assert placement is None  # only one schedulable node can hold CPU:1
    assert ex["chosen"] is None and ex["bundles"] == 2
    assert ex["rejected"]["drainy"] == "draining"
    assert ex["rejected"]["tiny"] == "resources"


def test_reason_for_no_node_mapping():
    assert sched_explain.reason_for_no_node(
        {"rejected": {"a": "draining"}}) == PendingReason.NODE_DRAINING
    assert sched_explain.reason_for_no_node(
        {"rejected": {"a": "draining", "b": "dead"}}) \
        == PendingReason.NODE_DRAINING
    # a draining cause marks an OTHERWISE-FEASIBLE host (infeasible nodes
    # read "resources" whatever their drain state), so it wins
    assert sched_explain.reason_for_no_node(
        {"rejected": {"a": "resources", "b": "draining"}}) \
        == PendingReason.NODE_DRAINING
    assert sched_explain.reason_for_no_node(
        {"rejected": {"a": "resources"}}) == PendingReason.NO_RESOURCES
    assert sched_explain.reason_for_no_node(
        {"rejected": {}}) == PendingReason.NO_RESOURCES
    assert sched_explain.reason_for_no_node(None) \
        == PendingReason.NO_RESOURCES


def test_decision_ring_bounds_and_age_out():
    """The GCS decision ring is bounded by count AND age."""
    from ray_tpu.core.gcs import GcsServer
    try:
        set_config(Config(sched_decision_ring_len=100,
                          sched_decision_max_age_s=60.0))
        gcs = GcsServer()

        async def drive():
            await gcs.handle_add_sched_decisions(
                [{"ts": time.time(), "kind": "task", "id": f"t{i}",
                  "outcome": "no_node"} for i in range(500)])
            assert len(gcs.sched_decisions) == 100  # count-bounded
            # age-out: a stale cohort is dropped on the next touch
            gcs.sched_decisions.clear()
            old = time.time() - 3600
            await gcs.handle_add_sched_decisions(
                [{"ts": old, "kind": "task", "id": "stale",
                  "outcome": "no_node"}])
            fresh = [{"ts": time.time(), "kind": "task", "id": "fresh",
                      "outcome": "no_node"}]
            await gcs.handle_add_sched_decisions(fresh)
            got = await gcs.handle_get_sched_decisions(limit=100)
            assert [r["id"] for r in got] == ["fresh"]
            # id filtering
            got = await gcs.handle_get_sched_decisions(id="fresh")
            assert len(got) == 1
            got = await gcs.handle_get_sched_decisions(id="absent")
            assert got == []

        asyncio.run(drive())
    finally:
        reset_config()


def test_loop_busy_fraction_sampling():
    """The loop monitor's busy fraction separates a spinning loop from an
    idle one (the thread-CPU clock sampled from inside the loop)."""
    from ray_tpu.util.loop_monitor import LoopMonitor

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        mon = LoopMonitor(loop, source="", busy_enabled=True,
                          interval_s=0.05)
        mon.start()
        time.sleep(0.8)
        idle = mon.busy_fraction
        assert idle < 0.5  # parked in epoll

        def spin():
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.03:
                pass
            loop.call_soon(spin)

        loop.call_soon_threadsafe(spin)
        time.sleep(1.5)
        assert mon.busy_fraction > 0.3, mon.busy_fraction
        mon.stop()
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
        loop.close()


def test_rpc_busy_attribution_excludes_awaits():
    """_BusyTimed attribution: a handler that PARKS attributes ~nothing;
    a handler that computes attributes its synchronous time — the
    distinction raytpu_rpc_server_seconds (wall) cannot make."""

    class H:
        async def handle_park(self):
            await asyncio.sleep(0.5)
            return "parked"

        async def handle_spin(self):
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.2:
                pass
            return "spun"

    busy = {}
    server = RpcServer(H())
    server.busy_cb = lambda m, s: busy.__setitem__(
        m, busy.get(m, 0.0) + s)
    run_async(server.start())
    client = RpcClient(server.address)
    try:
        assert run_async(client.call("park")) == "parked"
        assert run_async(client.call("spin")) == "spun"
        assert busy["spin"] >= 0.15, busy
        assert busy["park"] < 0.1, busy
    finally:
        run_async(client.close(), timeout=5)
        run_async(server.stop(), timeout=5)


# ------------------------------------------------- cluster: reason stamps

def _task_events(name=None, state=None, reason=None):
    from ray_tpu.util import state as state_api
    evs = state_api.list_tasks(limit=10000)
    out = []
    for e in evs:
        if name is not None and e.get("name") != name:
            continue
        if state is not None and e.get("state") != state:
            continue
        if reason is not None and e.get("reason") != reason:
            continue
        out.append(e)
    return out


@pytest.mark.timeout(120)
def test_infeasible_task_no_resources_end_to_end():
    """An infeasible ask is diagnosable from explain output ALONE: typed
    reason, per-node rejection cause, and the decision trail."""
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(resources={"GPU": 1})
        def never():
            return 0

        ref = never.remote()
        from ray_tpu.util import state as state_api
        _wait(lambda: _task_events("never", "PENDING",
                                   PendingReason.NO_RESOURCES),
              30, "NO_RESOURCES stamp to flush")
        tid = _task_events("never")[0]["task_id"]
        report = state_api.explain(tid)
        assert report["kind"] == "task"
        assert report["pending_reason"] == PendingReason.NO_RESOURCES
        assert report["state"] == "PENDING"
        decisions = report["decisions"]
        assert decisions, "no decision records for the stuck task"
        rec = decisions[-1]
        assert rec["outcome"] == "no_node"
        assert "resources" in set(rec["rejected"].values())
        assert rec["label"] == "never"
        # rollup matches reality: exactly one task pending, on resources
        summary = state_api.summarize_tasks()
        assert summary["pending_reasons"].get(
            PendingReason.NO_RESOURCES) == 1
        del ref
    finally:
        ray_tpu.shutdown()


@pytest.mark.timeout(120)
def test_admission_gate_reason_stamped():
    """A gate-parked burst stamps ADMISSION_GATE on the parked
    submission (and everything still completes)."""
    ray_tpu.init(num_cpus=1,
                 _system_config={"submit_inflight_limit": 2})
    try:
        @ray_tpu.remote
        def slow():
            time.sleep(0.5)
            return 1

        # 2 in flight fill the window; the 3rd .remote() parks on the
        # gate (driver thread) until a completion drains it
        refs = [slow.remote() for _ in range(3)]
        assert sum(ray_tpu.get(refs, timeout=60)) == 3
        from ray_tpu.core.core_worker import global_worker
        assert global_worker().admission_gate.blocked_total >= 1
        _wait(lambda: _task_events("slow", "PENDING",
                                   PendingReason.ADMISSION_GATE),
              20, "ADMISSION_GATE stamp to flush")
    finally:
        ray_tpu.shutdown()


@pytest.mark.timeout(180)
def test_backpressured_lease_queue_reason_and_counters():
    """lease_queue_max_depth=1: a second pool's lease request is answered
    with backpressure while the first pool's spare request holds the
    queue slot — the typed reason lands on the task, the reject counter
    on the node, and everything still completes."""
    ray_tpu.init(num_cpus=1,
                 _system_config={"lease_queue_max_depth": 1})
    try:
        @ray_tpu.remote
        def hog():
            time.sleep(0.9)
            return 1

        @ray_tpu.remote
        def beta():
            return 2

        # 3 hogs on 1 CPU: one runs, the pool's lease request for the
        # queued rest PARKS at the agent (depth 1 = full)
        hogs = [hog.remote() for _ in range(3)]
        time.sleep(0.8)
        b = beta.remote()         # second pool -> backpressure reply
        assert sum(ray_tpu.get(hogs, timeout=60)) == 3
        assert ray_tpu.get(b, timeout=60) == 2
        _wait(lambda: _task_events("beta", "PENDING",
                                   PendingReason.BACKPRESSURED),
              20, "BACKPRESSURED stamp to flush")
        # agent-side reject accounting (always-on ints + metric mirror)
        from ray_tpu.core.api import _state
        agent = _state.node_agent
        assert agent._bp_rejects.get("depth", 0) >= 1
        snap = snapshot_registry()
        bp = snap.get("raytpu_sched_backpressure_total")
        assert bp is not None and any(
            dict(k).get("reason") == "depth" for k in bp["values"])
        # decision trail names the backpressure outcome
        from ray_tpu.util import state as state_api
        recs = state_api.sched_decisions(limit=200)
        assert any(r.get("outcome") == "backpressure" for r in recs)
    finally:
        ray_tpu.shutdown()


@pytest.mark.slow
@pytest.mark.timeout(180)
def test_draining_node_reason_via_preemption(ray_start_cluster):
    """The only node that could host the shape receives a preemption
    notice (the preempt/drain plane): tasks against it stamp
    NODE_DRAINING with the per-node cause in the decision record, and
    run after the drain is lifted... which cannot happen for a REAL
    preemption — so here the shape is re-homed by adding a fresh node
    carrying the resource, exactly the operator runbook."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    special = cluster.add_node(num_cpus=2, resources={"special": 1})
    assert cluster.wait_for_nodes(2)
    cluster.connect_driver()

    # a lease must be outstanding on the node or the graceful drain
    # completes instantly and deregisters (nothing to wait for)
    @ray_tpu.remote(resources={"special": 0.5})
    def occupy():
        time.sleep(12.0)
        return 7

    pin = occupy.remote()
    from ray_tpu.util import state as state_api
    _wait(lambda: _task_events("occupy", "RUNNING"), 40,
          "occupy to start on the special node")

    # deliver a long preemption notice to the special node
    client = RpcClient(special.address)
    try:
        assert run_async(client.call("drain_self", notice_s=120.0))
    finally:
        run_async(client.close(), timeout=5)

    from ray_tpu.core.core_worker import global_worker
    w = global_worker()

    def _draining_visible():
        view = run_async(w.gcs.call("get_cluster_view"))
        return any(v.get("draining") for v in view.values())

    _wait(_draining_visible, 30, "draining flag to reach the GCS view")

    @ray_tpu.remote(resources={"special": 1})
    def needs_special():
        return 42

    ref = needs_special.remote()
    _wait(lambda: _task_events("needs_special", "PENDING",
                               PendingReason.NODE_DRAINING),
          40, "NODE_DRAINING stamp to flush")
    tid = _task_events("needs_special")[0]["task_id"]
    report = state_api.explain(tid)
    assert report["pending_reason"] == PendingReason.NODE_DRAINING
    assert "draining" in set(
        (report["decisions"][-1].get("rejected") or {}).values())
    # the runbook's fix: bring up replacement capacity
    cluster.add_node(num_cpus=2, resources={"special": 1})
    assert ray_tpu.get(ref, timeout=90) == 42
    assert ray_tpu.get(pin, timeout=90) == 7


@pytest.mark.timeout(120)
def test_waiting_deps_actor_call_reason():
    """A call parked behind a slow actor __init__ stamps WAITING_DEPS —
    the dependency is the actor itself."""
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        class Slow:
            def __init__(self):
                time.sleep(1.2)

            def ping(self):
                return "up"

        a = Slow.remote()
        r = a.ping.remote()
        assert ray_tpu.get(r, timeout=60) == "up"
        _wait(lambda: _task_events(state="PENDING",
                                   reason=PendingReason.WAITING_DEPS),
              20, "WAITING_DEPS stamp to flush")
    finally:
        ray_tpu.shutdown()


@pytest.mark.timeout(120)
def test_explain_cli_on_stuck_task(capsys):
    """`raytpu explain <id>` prints the whole trail: state, typed
    reason, transition timeline and the rejection causes."""
    from ray_tpu.scripts import cli

    ray_tpu.init(num_cpus=1)
    try:
        @ray_tpu.remote(resources={"accelerator": 4})
        def wedged():
            return 0

        ref = wedged.remote()
        _wait(lambda: _task_events("wedged", "PENDING"),
              30, "pending stamp to flush")
        tid = _task_events("wedged")[0]["task_id"]
        cli.cmd_explain(argparse.Namespace(id=tid, json=False))
        out = capsys.readouterr().out
        assert "NO_RESOURCES" in out
        assert "PENDING" in out and "wedged" in out
        assert "no_node" in out and "resources" in out
        # and the PG path: an infeasible placement group explains itself
        pg = ray_tpu.placement_group([{"CPU": 64}])
        assert not pg.ready(timeout=2)
        cli.cmd_explain(argparse.Namespace(id=pg.id, json=False))
        out = capsys.readouterr().out
        assert "pg" in out and "NO_RESOURCES" in out
        ray_tpu.remove_placement_group(pg)
        del ref
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------- kill switch / A/B

def _series_fingerprint():
    """Count of values per raytpu_sched_/raytpu_loop_busy/raytpu_gcs_
    series — the registry is process-global, so the kill-switch test
    asserts NO NEW values appear, not that none ever existed."""
    snap = snapshot_registry()
    out = {}
    for name, s in snap.items():
        if name.startswith(("raytpu_sched_", "raytpu_loop_busy",
                            "raytpu_gcs_")):
            vals = s.get("values") or s.get("count") or {}
            out[name] = (len(vals), sum(vals.values()))
    return out


@pytest.mark.timeout(120)
def test_sched_metrics_kill_switch_zero_new_series():
    """sched_metrics_enabled=False ⇒ zero new raytpu_sched_*/
    raytpu_loop_busy*/raytpu_gcs_* samples, while the EXPLAIN half
    (reason stamps, decision records) still answers."""
    before = _series_fingerprint()
    ray_tpu.init(num_cpus=2,
                 _system_config={"sched_metrics_enabled": False,
                                 "lease_queue_max_depth": 1})
    try:
        @ray_tpu.remote
        def f():
            return 1

        assert sum(ray_tpu.get([f.remote() for _ in range(20)],
                               timeout=60)) == 20

        @ray_tpu.remote(resources={"GPU": 1})
        def g():
            return 2

        ref = g.remote()
        _wait(lambda: _task_events("g", "PENDING",
                                   PendingReason.NO_RESOURCES),
              30, "explain half still stamping")
        from ray_tpu.util import state as state_api
        stats = state_api.sched_stats()
        assert stats["sched_metrics_enabled"] is False
        assert not stats["handler_busy_s"]  # busy attribution off
        assert state_api.explain(
            _task_events("g")[0]["task_id"])["decisions"]
        # give monitors/flushers a tick, then compare
        time.sleep(1.0)
        assert _series_fingerprint() == before
        del ref
    finally:
        ray_tpu.shutdown()


@pytest.mark.timeout(180)
def test_two_node_pending_reason_rollup_matches_reality(ray_start_cluster):
    """2-node acceptance: summarize_tasks()["pending_reasons"] counts
    exactly the wedged tasks under their typed reason while runnable work
    keeps flowing, and the saturation stats answer cluster-wide."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    assert cluster.wait_for_nodes(2)
    cluster.connect_driver()

    @ray_tpu.remote
    def ok():
        return 1

    assert sum(ray_tpu.get([ok.remote() for _ in range(8)],
                           timeout=60)) == 8

    @ray_tpu.remote(resources={"GPU": 1})
    def wedged():
        return 0

    refs = [wedged.remote() for _ in range(3)]
    from ray_tpu.util import state as state_api

    def rollup_settled():
        pr = state_api.summarize_tasks()["pending_reasons"]
        return pr.get(PendingReason.NO_RESOURCES) == 3
    _wait(rollup_settled, 40, "rollup to count 3 NO_RESOURCES tasks")
    pr = state_api.summarize_tasks()["pending_reasons"]
    # nothing else is pending: the 8 ok() tasks all FINISHED
    assert pr.get(PendingReason.NO_RESOURCES) == 3
    assert sum(pr.values()) == 3, pr
    # saturation half: the GCS names its busiest handlers + loop fraction
    stats = state_api.sched_stats()
    assert stats["loop_busy_fraction"] is not None
    assert stats["top_handlers"], "no handler busy attribution"
    busiest = dict(stats["handler_busy_s"])
    assert busiest.get("heartbeat", 0) > 0  # 2 nodes heartbeating
    del refs
