"""Structured event framework (reference: src/ray/util/event.h:41 RAY_EVENT
+ dashboard/modules/event)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import events


def test_record_and_list(ray_start_regular):
    events.record("INFO", "test", "hello world", key="v1")
    events.record("WARNING", "test", "watch out", node="n1")
    events.record("ERROR", "other", "boom")

    evs = events.list_events()
    assert len(evs) >= 3
    assert evs[0]["ts"] >= evs[-1]["ts"]  # newest first

    warns = events.list_events(severity="WARNING")
    assert warns and all(e["severity"] == "WARNING" for e in warns)
    assert warns[0]["labels"] == {"node": "n1"}

    mine = events.list_events(source="other")
    assert all(e["source"] == "other" for e in mine)
    with pytest.raises(ValueError):
        events.record("LOUD", "test", "nope")


def test_events_visible_from_workers_and_dashboard(ray_start_regular):
    pytest.importorskip("aiohttp")

    @ray_tpu.remote
    def emit():
        from ray_tpu.util import events as ev
        ev.record("ERROR", "worker-task", "task-side event", attempt="1")
        return True

    assert ray_tpu.get(emit.remote(), timeout=60)
    evs = events.list_events(source="worker-task")
    assert evs and evs[0]["message"] == "task-side event"

    from ray_tpu.dashboard.head import start_dashboard, stop_dashboard
    port = start_dashboard(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/events?severity=ERROR",
                timeout=30) as r:
            body = json.loads(r.read())
        assert any(e["source"] == "worker-task" for e in body)
    finally:
        stop_dashboard()

