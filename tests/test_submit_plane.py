"""Submission-plane invariants: event sampling must never lose accounting.

With ``task_event_sample_n = N``, only 1-in-N tasks ship their
SUBMITTED/RUNNING event payloads — but the discipline has three hard
rules this file pins down end to end:

* terminal events (FINISHED/FAILED) ALWAYS emit, so ``summarize_tasks``
  (which keys on the newest event per task) still counts every task
  exactly;
* the sampling coin is the task id's last byte, so a task's whole trail
  is in or out — ``raytpu explain`` answers for every task that reached
  a terminal state, sampled-out or not;
* what sampling hides, counters preserve: the owner's exact
  emitted/sampled/freelist counters piggyback the event flush into
  ``sched_stats()["submit_plane"]``.

Plus the off-switch: ``submit_plane_native_enabled=False`` must restore
the unpooled path with full (unsampled) event trails.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util import state

SAMPLE_N = 8
# > 1.0s event-flush cadence, with margin for a busy box
FLUSH_WAIT_S = 1.8


def _drain_events():
    time.sleep(FLUSH_WAIT_S)


@pytest.fixture
def sampled_cluster():
    ray_tpu.init(num_cpus=2,
                 _system_config={"task_event_sample_n": SAMPLE_N})
    try:
        yield
    finally:
        ray_tpu.shutdown()


def _run_batch(n):
    @ray_tpu.remote
    def sp_noop():
        return 1

    refs = [sp_noop.remote() for _ in range(n)]
    assert ray_tpu.get(refs) == [1] * n
    return [r.task_id() for r in refs]


def test_sampling_keeps_terminal_accounting_exact(sampled_cluster):
    N = 120
    tids = _run_batch(N)
    _drain_events()

    # Terminals always emit: the rollup counts every task exactly even
    # though ~7/8 of SUBMITTED/RUNNING payloads were sampled away.
    summ = state.summarize_tasks()
    assert summ["cluster"]["sp_noop"].get("FINISHED") == N

    # Per-task: every one of our tasks has a FINISHED event; tasks on the
    # sampled-out side of the coin have NO SUBMITTED/RUNNING payloads
    # (all-or-nothing trails), tasks on the emitted side kept theirs.
    events = state.list_tasks(limit=100_000)
    by_tid = {}
    ours = {t.hex() for t in tids}
    for ev in events:
        if ev.get("task_id") in ours:
            by_tid.setdefault(ev["task_id"], set()).add(ev.get("state"))
    sampled_out = [t for t in tids if t._bin[-1] % SAMPLE_N]
    emitted = [t for t in tids if not t._bin[-1] % SAMPLE_N]
    assert sampled_out and emitted, "need both coin classes to test"
    for t in tids:
        assert "FINISHED" in by_tid.get(t.hex(), set()), \
            f"terminal event sampled away for {t.hex()}"
    for t in sampled_out:
        assert not by_tid[t.hex()] & {"SUBMITTED", "RUNNING"}, \
            f"half-sampled trail for {t.hex()}"
    for t in emitted:
        assert "SUBMITTED" in by_tid[t.hex()]

    # explain answers for a task whose SUBMITTED/RUNNING was sampled out.
    trail = state.explain(sampled_out[0].hex())
    assert trail["kind"] == "task"
    assert trail["state"] == "FINISHED"


def test_counters_surface_what_sampling_hid(sampled_cluster):
    from ray_tpu.core.core_worker import global_worker
    N = 64
    tids = _run_batch(N)
    _drain_events()

    owner = global_worker().address
    planes = state.sched_stats().get("submit_plane") or {}
    assert owner in planes, f"no submit-plane counters for owner {owner}"
    c = planes[owner]
    assert c["sample_n"] == SAMPLE_N
    # every suppressed payload was counted: at least one suppression per
    # sampled-out task (its SUBMITTED), and every terminal emitted
    n_out = sum(1 for t in tids if t._bin[-1] % SAMPLE_N)
    assert c["events_sampled"] >= n_out
    assert c["events_emitted"] >= N
    assert c["events_shed"] == 0
    # the pooled plane actually ran warm: templates + free list hits
    assert c["native_enabled"] is True
    assert c["freelist_hits"] > 0


def test_disabled_plane_restores_full_event_trails():
    """The off switch is total: ctor path, per-spec encode, and an
    UNSAMPLED event trail for every task."""
    ray_tpu.init(num_cpus=2, _system_config={
        "submit_plane_native_enabled": False,
        "task_event_sample_n": 1,
    })
    try:
        tids = _run_batch(16)
        _drain_events()
        events = state.list_tasks(limit=100_000)
        ours = {t.hex() for t in tids}
        by_tid = {}
        for ev in events:
            if ev.get("task_id") in ours:
                by_tid.setdefault(ev["task_id"], set()).add(ev.get("state"))
        for t in tids:
            assert {"SUBMITTED", "FINISHED"} <= by_tid.get(t.hex(), set())
        from ray_tpu.core.core_worker import global_worker
        planes = state.sched_stats().get("submit_plane") or {}
        c = planes.get(global_worker().address)
        if c is not None:
            assert c["native_enabled"] is False
            assert c["events_sampled"] == 0
    finally:
        ray_tpu.shutdown()


def test_actor_calls_sampled_and_counted(sampled_cluster):
    """Actor method calls ride the same plane: terminals exact under
    sampling, and the per-handle template path stays correct."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    N = 40
    vals = ray_tpu.get([c.bump.remote() for _ in range(N)])
    assert vals == list(range(1, N + 1))
    _drain_events()
    summ = state.summarize_tasks()
    assert summ["cluster"]["bump"].get("FINISHED") == N
