"""Horizontal control plane: multi-process GCS shards + routing.

Covers the PR-13 split (router = globally-ordered concerns; shard
processes = key-partitioned hot traffic): partition-helper stability,
client->shard direct routing vs router proxy equivalence, fan-in ring
merges, per-shard saturation stats, shard-process supervision (kill ->
respawn at the same index), and the full runtime riding on a sharded
control plane.
"""

import time

import pytest

from ray_tpu.core.config import Config, reset_config, set_config
from ray_tpu.core.gcs import GcsServer
from ray_tpu.core.gcs_router import (FANIN_METHODS, KEYED_METHODS,
                                     ShardedGcsClient, shard_for,
                                     shard_index)
from ray_tpu.core.rpc import RpcClient, run_async


@pytest.fixture(autouse=True)
def _cfg():
    yield
    reset_config()


def _sharded_gcs(n=2, **cfg):
    set_config(Config(gcs_shard_processes=n, **cfg))
    gcs = GcsServer()
    run_async(gcs.start(), timeout=60)
    return gcs


# ------------------------------------------------------------ partitioning

def test_shard_index_is_stable_and_process_independent():
    """The partition helper must agree across processes and incarnations:
    crc32-based, never the salted builtin hash()."""
    import subprocess
    import sys
    vals = {ns: shard_index(ns, 4)
            for ns in ("default", "funcs", "workflow", "serve")}
    assert all(0 <= v < 4 for v in vals.values())
    assert shard_index("anything", 1) == 0
    # a FRESH interpreter (different hash salt) computes the same map
    out = subprocess.check_output(
        [sys.executable, "-c",
         "from ray_tpu.core.gcs_router import shard_index\n"
         "print([shard_index(ns, 4) for ns in "
         "('default', 'funcs', 'workflow', 'serve')])"],
        env={"PYTHONPATH": "/root/repo", "PATH": "/usr/bin:/bin",
             "PYTHONHASHSEED": "random"})
    assert eval(out.decode()) == [vals["default"], vals["funcs"],
                                  vals["workflow"], vals["serve"]]


def test_shard_for_routes_keyed_and_fanin_methods():
    for method in KEYED_METHODS:
        idx = shard_for(method, {"ns": "workflow"}, "me", 4)
        assert idx == shard_index("workflow", 4)
    for method in FANIN_METHODS:
        assert shard_for(method, {}, "me", 4) == shard_index("me", 4)
    # router methods stay unrouted
    assert shard_for("register_node", {}, "me", 4) is None
    assert shard_for("kv_get", {"ns": "x"}, "me", 0) is None


# ------------------------------------------------------- routing + merging

def test_proxy_and_direct_routes_see_one_kv():
    gcs = _sharded_gcs(2)
    try:
        # write through the router proxy, read direct off the owning shard
        assert run_async(gcs.handle_kv_put(ns="nsa", key="k", value=b"v"))
        owner = shard_index("nsa", 2)
        c = RpcClient(gcs._shard_addrs[owner])
        assert run_async(c.call("kv_get", ns="nsa", key="k")) == b"v"
        run_async(c.close())
        # write direct via the facade, read through the proxy
        cli = ShardedGcsClient(gcs.address)
        cli.set_shard_map(gcs._shard_addrs)
        run_async(cli.call_retry("kv_put", ns="nsb", key="k2", value=b"w"))
        assert run_async(gcs.handle_kv_get(ns="nsb", key="k2")) == b"w"
        assert run_async(gcs.handle_kv_exists(ns="nsb", key="k2"))
        assert run_async(gcs.handle_kv_keys(ns="nsb")) == ["k2"]
        assert run_async(gcs.handle_kv_del(ns="nsb", key="k2"))
        assert run_async(gcs.handle_kv_get(ns="nsb", key="k2")) is None
        run_async(cli.close())
    finally:
        run_async(gcs.stop(), timeout=10)


def test_fanin_rings_merge_across_shards():
    gcs = _sharded_gcs(2)
    try:
        # two writers whose identities land on DIFFERENT shards
        ids = [f"writer-{i}" for i in range(64)]
        a = next(i for i in ids if shard_index(i, 2) == 0)
        b = next(i for i in ids if shard_index(i, 2) == 1)
        for ident, tid in ((a, "task-a"), (b, "task-b")):
            cli = ShardedGcsClient(gcs.address, identity=ident)
            cli.set_shard_map(gcs._shard_addrs)
            run_async(cli.call("add_task_events", events=[
                {"task_id": tid, "name": "t", "state": "FINISHED",
                 "ts": time.time()}]))
            run_async(cli.call("add_sched_decisions", records=[
                {"kind": "task", "id": tid, "outcome": "granted",
                 "ts": time.time()}]))
            run_async(cli.call("add_object_events", events=[
                {"object_id": "oid-" + tid, "event": "CREATED",
                 "ts": time.time()}]))
            run_async(cli.close())
        # state-API reads merge BOTH shards' slices at the router
        evs = run_async(gcs.handle_list_task_events(limit=10))
        assert {e["task_id"] for e in evs} == {"task-a", "task-b"}
        decs = run_async(gcs.handle_get_sched_decisions(limit=10))
        assert {d["id"] for d in decs} == {"task-a", "task-b"}
        # explain finds the trail wherever its writer's shard was
        ex = run_async(gcs.handle_explain(id="task-b"))
        assert ex["kind"] == "task" and ex["events"]
        assert [d["id"] for d in ex["decisions"]] == ["task-b"]
        exo = run_async(gcs.handle_explain_object(id="oid-task-a"))
        assert exo["kind"] == "object"
    finally:
        run_async(gcs.stop(), timeout=10)


def test_sched_stats_aggregates_per_shard():
    gcs = _sharded_gcs(2)
    try:
        run_async(gcs.handle_kv_put(ns="x", key="k", value=b"v"))
        stats = run_async(gcs.handle_sched_stats())
        assert set(stats["shards"].keys()) == {"0", "1"}
        assert set(stats["shard_busy_fractions"].keys()) == \
            {"gcs_shard:0", "gcs_shard:1"}
        for st in stats["shards"].values():
            assert "handler_busy_s" in st and "pid" in st
        # the shard that owns ns "x" attributed the kv_put busy time
        owner = str(shard_index("x", 2))
        assert "kv_put" in stats["shards"][owner]["handler_calls"]
    finally:
        run_async(gcs.stop(), timeout=10)


# ---------------------------------------------------------- supervision

@pytest.mark.timeout(120)
def test_shard_process_killed_is_respawned_and_restores(tmp_path):
    set_config(Config(gcs_shard_processes=2))
    snap = str(tmp_path / "gcs.snap")
    gcs = GcsServer(persistence_path=snap)
    run_async(gcs.start(), timeout=60)
    try:
        run_async(gcs.handle_kv_put(ns="nsa", key="k", value=b"v"))
        owner = shard_index("nsa", 2)
        victim = gcs._shard_procs[owner]
        old_addr = gcs._shard_addrs[owner]
        victim.kill()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if (gcs._shard_procs[owner] is not victim
                    and gcs._shard_procs[owner].poll() is None):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("shard was not respawned")
        assert gcs._shard_addrs[owner] != old_addr
        # the replacement restored ITS snapshot: the key survives, served
        # through the router proxy (new address) transparently
        assert run_async(gcs.handle_kv_get(ns="nsa", key="k")) == b"v"
        # a facade holding the STALE map falls back to the router and
        # self-heals on the next map fetch
        cli = ShardedGcsClient(gcs.address)
        cli.set_shard_map([old_addr] * 2 if owner == 0
                          else [gcs._shard_addrs[0], old_addr])
        assert run_async(cli.call_retry(
            "kv_get", ns="nsa", key="k", _idempotent=False,
            _timeout=10, _attempts=1)) == b"v"
        run_async(cli.close())
    finally:
        run_async(gcs.stop(), timeout=10)


# ------------------------------------------------------------- end to end

@pytest.mark.timeout(180)
def test_runtime_on_sharded_control_plane():
    """The full runtime (tasks, named actors, PGs, function registry via
    sharded KV, task-event plane) runs against gcs_shard_processes=2."""
    import ray_tpu
    from ray_tpu.utils.testing import CPU_WORKER_ENV

    ray_tpu.init(num_cpus=2, worker_env=dict(CPU_WORKER_ENV),
                 _system_config={"gcs_shard_processes": 2})
    try:
        @ray_tpu.remote
        def double(i):
            return i * 2

        assert ray_tpu.get([double.remote(i) for i in range(50)]) == \
            [i * 2 for i in range(50)]

        @ray_tpu.remote(num_cpus=0)
        class Box:
            def __init__(self):
                self.v = 0

            def bump(self):
                self.v += 1
                return self.v

        b = Box.options(name="shard-box").remote()
        assert ray_tpu.get(b.bump.remote()) == 1

        pg = ray_tpu.placement_group([{"CPU": 1}])
        assert pg.ready(timeout=30)
        ray_tpu.remove_placement_group(pg)

        # the task-event plane (owner flush -> its shard; state API merge)
        from ray_tpu.util import state
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            tasks = state.list_tasks(limit=500)
            if any(t.get("name") == "double" for t in tasks):
                break
            time.sleep(0.25)
        assert any(t.get("name") == "double" for t in tasks)
        stats = state.sched_stats()
        assert set(stats["shards"].keys()) == {"0", "1"}
    finally:
        ray_tpu.shutdown()
