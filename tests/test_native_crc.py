"""Native CRC-32C component (ray_tpu/native/crc32c.cpp — the data-path
checksum behind TFRecord framing and the TensorBoard event writer)."""

import numpy as np
import pytest

from ray_tpu.native import load_crc32c


@pytest.fixture(scope="module")
def crc():
    fn = load_crc32c()
    if fn is None:
        pytest.skip("native crc32c unavailable (no g++)")
    return fn


def test_known_vectors(crc):
    # RFC 3720 / crc32c reference vectors
    assert crc(b"123456789") == 0xE3069283
    assert crc(b"") == 0x00000000
    assert crc(b"\x00" * 32) == 0x8A9136AA
    assert crc(b"\xff" * 32) == 0x62A8AB43


def test_matches_pure_python(crc):
    from ray_tpu.data.datasource import _CRC32C_TABLE  # noqa: F401
    # force the pure-python path for comparison
    import ray_tpu.data.datasource as ds

    def pure(data):
        saved = ds._crc32c_ext, ds._native_crc_state
        ds._crc32c_ext, ds._native_crc_state = None, "failed"
        try:
            return ds._crc32c(data)
        finally:
            ds._crc32c_ext, ds._native_crc_state = saved

    rng = np.random.default_rng(0)
    for n in (1, 7, 8, 9, 63, 64, 1000, 4096):
        buf = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert crc(buf) == pure(buf), n


def test_tfrecord_roundtrip_uses_native(tmp_path, crc):
    """The framing written through the (now native) masked CRC parses
    back — and matches what TF's reader would verify."""
    from ray_tpu.data.datasource import (_masked_crc32c, _tfrecord_frame)
    import struct

    payload = b"hello tfrecord"
    frame = _tfrecord_frame(payload)
    length = struct.unpack("<Q", frame[:8])[0]
    assert length == len(payload)
    (len_crc,) = struct.unpack("<I", frame[8:12])
    assert len_crc == _masked_crc32c(frame[:8])
    data = frame[12:12 + length]
    (data_crc,) = struct.unpack("<I", frame[12 + length:16 + length])
    assert data == payload
    assert data_crc == _masked_crc32c(payload)


def test_throughput_sanity(crc):
    """Native path must beat the pure-python loop by a wide margin —
    this is the reason the component exists (soft gate: 10x)."""
    import time

    import ray_tpu.data.datasource as ds

    buf = bytes(1_000_000)
    t0 = time.perf_counter()
    for _ in range(5):
        crc(buf)
    native_s = time.perf_counter() - t0

    saved = ds._crc32c_ext, ds._native_crc_state
    ds._crc32c_ext, ds._native_crc_state = None, "failed"
    try:
        t0 = time.perf_counter()
        ds._crc32c(buf[:100_000])
        pure_s = (time.perf_counter() - t0) * 10  # scale to 1MB
    finally:
        ds._crc32c_ext, ds._native_crc_state = saved
    assert native_s / 5 < pure_s / 10, (native_s, pure_s)
