"""Dashboard REST + tracing + OOM-policy tests (reference:
dashboard/modules tests, `ray timeline`, worker_killing_policy_test.cc)."""

import sys
import time

import pytest

import ray_tpu


def test_dashboard_rest_surface(ray_start_regular, tmp_path):
    import requests

    from ray_tpu.dashboard import start_dashboard, head

    port = start_dashboard()
    base = f"http://127.0.0.1:{port}/api"
    try:
        assert requests.get(f"{base}/healthz", timeout=10).text == "success"
        cluster = requests.get(f"{base}/cluster", timeout=10).json()
        assert cluster["nodes"] >= 1
        assert "CPU" in cluster["resources_total"]

        @ray_tpu.remote
        class Dummy:
            def ping(self):
                return 1

        a = Dummy.options(name="dash-actor").remote()
        ray_tpu.get(a.ping.remote(), timeout=30)
        actors = requests.get(f"{base}/actors", timeout=10).json()
        assert any(x.get("name") == "dash-actor" for x in actors)

        nodes = requests.get(f"{base}/nodes", timeout=10).json()
        assert len(nodes) >= 1 and nodes[0]["Alive"]

        # job submission through REST
        r = requests.post(f"{base}/jobs", json={
            "entrypoint": f"{sys.executable} -c 'print(\"REST_JOB_OK\")'"},
            timeout=60)
        job_id = r.json()["job_id"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            info = requests.get(f"{base}/jobs/{job_id}", timeout=10).json()
            if info["status"] in ("SUCCEEDED", "FAILED"):
                break
            time.sleep(0.5)
        assert info["status"] == "SUCCEEDED"
        logs = requests.get(f"{base}/jobs/{job_id}/logs", timeout=10).text
        assert "REST_JOB_OK" in logs

        # timeline exports chrome-trace events
        trace = requests.get(f"{base}/timeline", timeout=10).json()
        assert isinstance(trace, list)
    finally:
        head.stop_dashboard()


def test_chrome_trace_and_spans(ray_start_regular, tmp_path):
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def traced_task():
        return 1

    with tracing.span("user-phase", step=1):
        ray_tpu.get(traced_task.remote(), timeout=30)

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        trace = tracing.chrome_trace()
        slices = [e for e in trace if e["ph"] == "X"]
        if any(e["name"] == "traced_task" for e in slices) and \
                any(e["name"] == "user-phase" for e in slices):
            break
        time.sleep(0.3)
    names = {e["name"] for e in trace if e["ph"] == "X"}
    assert "traced_task" in names, names
    assert "user-phase" in names, names
    span_ev = next(e for e in trace
                   if e["name"] == "user-phase" and e["ph"] == "X")
    assert span_ev["dur"] > 0
    # user attributes survive; trace/span ids ride along for flow arrows
    assert span_ev["args"]["step"] == 1
    assert span_ev["args"].get("trace_id") and span_ev["args"].get("span_id")

    out = tracing.export_chrome_trace(str(tmp_path / "trace.json"))
    import json
    assert json.load(open(out))


def test_oom_victim_policy():
    """Retriable-LIFO: newest leased task worker first; actors spared."""
    from ray_tpu.core.node_agent import NodeAgent, WorkerHandle

    agent = NodeAgent.__new__(NodeAgent)  # policy is pure over .workers

    def mk(wid, state, actor, t):
        w = WorkerHandle(worker_id=wid, proc=None, state=state, is_actor=actor)
        w.registered.set()  # only registered (task-running) workers qualify
        return w
    agent.workers = {}
    assert agent._pick_oom_victim() is None

    w_old = mk("old-task", "LEASED", False, 1)
    w_old.leased_at = 1.0
    w_new = mk("new-task", "LEASED", False, 2)
    w_new.leased_at = 2.0
    w_actor = mk("actor", "LEASED", True, 3)
    w_actor.leased_at = 3.0
    w_idle = mk("idle", "IDLE", False, 4)
    agent.workers = {w.worker_id: w
                     for w in (w_old, w_new, w_actor, w_idle)}
    assert agent._pick_oom_victim() is w_new  # newest TASK, not the actor
    del agent.workers["new-task"], agent.workers["old-task"]
    assert agent._pick_oom_victim() is w_actor  # actors only as last resort
