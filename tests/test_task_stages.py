"""Task-lifecycle stage breakdown + runtime self-instrumentation tests.

The stage pipeline under test: the executor stamps dep_fetch / arg_deser /
execute / result_put wall-clock spans into a STAGES task event
(``CoreWorker._record_stages``), the owner stamps queue (submit->dispatch)
and total (submit->terminal) durations onto the RUNNING/FINISHED events,
``state.summarize_tasks`` rolls them into percentiles, the timeline renders
them as nested sub-slices, and ``raytpu_task_stage_seconds`` plus the RPC
histograms and node gauges land on the agent's /metrics endpoint.
"""

import time

import pytest

import ray_tpu


def _events_for(name: str):
    evs = ray_tpu.timeline()
    return [e for e in evs if (e.get("name") or "").startswith(name)]


def _wait_for_stages(name: str, timeout: float = 20.0):
    """Wait until the worker's STAGES event and the owner's FINISHED event
    for `name` both reached the GCS (separate 1 s flush loops)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        evs = _events_for(name)
        stages = next((e for e in evs if e.get("state") == "STAGES"), None)
        done = next((e for e in evs if e.get("state") == "FINISHED"), None)
        if stages is not None and done is not None:
            return evs, stages, done
        time.sleep(0.25)
    raise AssertionError(f"no STAGES+FINISHED events for {name!r} flushed")


def test_task_stage_breakdown_round_trip(ray_start_regular):
    """A round-tripped task yields every lifecycle stage with non-negative
    durations summing to no more than the driver-observed wall clock."""

    @ray_tpu.remote
    def consume(x):
        return len(x)

    payload = ray_tpu.put(b"x" * (1 << 20))  # plasma-sized: real dep fetch
    t0 = time.time()
    assert ray_tpu.get(consume.remote(payload), timeout=60) == 1 << 20
    wall = time.time() - t0

    evs, stages_ev, done_ev = _wait_for_stages("consume")
    stages = stages_ev["stages"]
    for stage in ("dep_fetch", "arg_deser", "execute", "result_put"):
        assert stage in stages, f"missing stage {stage}: {stages}"
        start, dur = stages[stage]
        assert start > 0 and dur >= 0.0
    # executor stages all happen inside the submit->get window
    assert sum(d for _t, d in stages.values()) <= wall + 0.05
    # owner-side stamps: queueing rides RUNNING, the whole wall clock rides
    # the terminal event
    run_ev = next(e for e in evs if e.get("state") == "RUNNING")
    assert run_ev.get("queue_s") is not None and run_ev["queue_s"] >= 0.0
    assert done_ev.get("total_s") is not None
    assert done_ev["total_s"] <= wall + 0.05
    assert done_ev["total_s"] >= sum(
        d for _t, d in stages.values()) - 1e-6


def test_summarize_tasks_stage_percentiles(ray_start_regular):
    from ray_tpu.util import state

    @ray_tpu.remote
    def tick():
        return 1

    assert ray_tpu.get([tick.remote() for _ in range(4)], timeout=60) == [1] * 4
    _wait_for_stages("tick")
    summary = state.summarize_tasks()
    lat = summary["stage_latency"]
    for stage in ("queue", "total", "execute", "result_put"):
        assert stage in lat, f"missing {stage} in {sorted(lat)}"
        s = lat[stage]
        assert s["count"] >= 1
        assert 0.0 <= s["p50"] <= s["p90"] <= s["p99"] <= s["max"]
    # FINISHED must still be counted as the task state (STAGES events are
    # annotations, not state transitions)
    assert summary["cluster"]["tick"].get("FINISHED", 0) >= 4


def test_chrome_trace_breakdown_subslices(ray_start_regular, tmp_path):
    """`raytpu timeline --breakdown` writes task slices containing nested
    per-stage sub-slices (same pid/tid, within the task slice's bounds)."""
    import json

    from ray_tpu.util import tracing

    @ray_tpu.remote
    def work(x):
        return x + 1

    assert ray_tpu.get(work.remote(1), timeout=60) == 2
    _wait_for_stages("work")

    out = tracing.export_chrome_trace(str(tmp_path / "t.json"),
                                      breakdown=True)
    trace = json.load(open(out))
    tasks = [e for e in trace if e.get("cat") == "task" and e.get("ph") == "X"
             and e.get("name") == "work"]
    assert tasks, "no task slice for work"
    task = tasks[0]
    subs = [e for e in trace if e.get("cat") == "stage"
            and e.get("args", {}).get("task") == "work"]
    names = {e["name"] for e in subs}
    assert {"dep_fetch", "arg_deser", "execute", "result_put"} <= names
    for e in subs:
        # nested: same row as the parent slice, inside its time bounds
        assert e["pid"] == task["pid"] and e["tid"] == task["tid"]
        assert e["ts"] >= task["ts"] - 1.0
        assert e["ts"] + e["dur"] <= task["ts"] + task["dur"] + 1e3
    # without --breakdown the stage sub-slices are absent
    plain = tracing.chrome_trace(breakdown=False)
    assert not [e for e in plain if e.get("cat") == "stage"]


def test_open_running_slices_keep_flow_arrows():
    """Satellite regression: a still-open RUNNING slice must emit its flow
    events (parent arrows) instead of dropping them with the instant
    fallback."""
    from ray_tpu.util import tracing

    events = [
        {"task_id": "aaaa", "name": "parent_span", "state": "SPAN",
         "ts": 1.0, "dur": 5.0, "worker": "w1",
         "trace_id": "t1", "span_id": "s-parent"},
        {"task_id": "bbbb", "name": "child_task", "state": "RUNNING",
         "ts": 2.0, "node_id": "n1",
         "trace_id": "t1", "span_id": "s-child", "parent_id": "s-parent"},
    ]
    trace = tracing.chrome_trace(events)
    finishes = [e for e in trace if e.get("ph") == "f"]
    assert any(e.get("id") == "s-parent" for e in finishes), \
        "open RUNNING slice dropped its parent flow arrow"
    starts = [e for e in trace if e.get("ph") == "s"]
    assert any(e.get("id") == "s-child" for e in starts)


def test_metrics_endpoint_serves_stage_rpc_and_node_gauges(ray_start_regular):
    """curl /metrics on a running node serves raytpu_task_stage_seconds,
    the RPC client/server histograms, and the shm/queue-depth gauges."""
    import requests

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote(), timeout=60) == 1
    nodes = ray_tpu.nodes()
    port = next(n["Labels"].get("metrics_port") for n in nodes
                if n["Labels"].get("metrics_port"))
    url = f"http://127.0.0.1:{port}/metrics"
    want = ("raytpu_task_stage_seconds_bucket",
            "raytpu_rpc_client_seconds_bucket",
            "raytpu_rpc_server_seconds_bucket",
            'stage="execute"')  # executor-side: arrives via worker flush
    deadline = time.monotonic() + 20
    body = ""
    while time.monotonic() < deadline:
        body = requests.get(url, timeout=10).text
        if all(w in body for w in want):
            break
        time.sleep(0.5)  # driver/worker registry flushes run every ~2 s
    for w in want:
        assert w in body, body[:3000]
    # stage series carry the stage tag
    assert 'stage="execute"' in body
    assert 'stage="queue"' in body
    # RPC byte counters and the in-flight gauge
    assert "raytpu_rpc_bytes_sent_total" in body
    assert "raytpu_rpc_bytes_received_total" in body
    assert "raytpu_rpc_client_inflight" in body
    # node telemetry gauges (agent registry, node-tagged)
    for g in ("raytpu_node_workers", "raytpu_node_lease_queue_len",
              "raytpu_object_store_bytes", "raytpu_object_store_free_bytes",
              "raytpu_object_store_largest_free_bytes",
              "raytpu_read_pins_outstanding", "raytpu_resource_total"):
        assert g in body, f"missing {g}"


def test_prometheus_label_escaping_regression():
    """fmt_tags must escape backslash, double-quote and newline in label
    values — arbitrary tag strings (exception reprs, paths) previously
    produced malformed exposition output."""
    from ray_tpu.util import metrics as m

    g = m.Gauge("raytpu_escape_regression_gauge", "x", tag_keys=("err",))
    g.set(1, tags={"err": 'quote:" backslash:\\ newline:\nEND'})
    text = m.render_prometheus(
        {"w": {"raytpu_escape_regression_gauge":
               g.snapshot()}})
    line = next(ln for ln in text.splitlines()
                if ln.startswith("raytpu_escape_regression_gauge{"))
    assert 'quote:\\"' in line
    assert "backslash:\\\\" in line
    assert "newline:\\nEND" in line  # literal backslash-n, not a line break


def test_metric_name_validation():
    """Prometheus name grammar: colons are legal, non-ASCII and dashes are
    not (the old ``isalnum`` check got both wrong)."""
    from ray_tpu.util import metrics as m

    m.Counter("raytpu_test:colon_total")  # valid per the Prometheus grammar
    for bad in ("9leading_digit", "has-dash", "häß", "sp ace", ""):
        with pytest.raises(ValueError):
            m.Counter(bad)
