"""Cluster-launcher tests: ``raytpu up / down / status`` over the GCE TPU
queued-resource provider with a fake transport (reference:
``python/ray/tests/test_cli.py`` driving ``ray up`` against mock
providers).  Zero network IO — the FakeTpuApi from the provider tests
models the QR lifecycle in memory."""

import json

import pytest

from ray_tpu.autoscaler.launcher import (ClusterLauncher, default_state_path,
                                         load_config)
from tests.test_autoscaler_providers import FakeTpuApi

CONFIG_YAML = """
cluster_name: testfleet
gcs_address: 10.0.0.1:6379
provider:
  type: gce_tpu
  project: proj
  zone: us-central2-b
  poll_interval_s: 0.01
available_node_types:
  v5e_8:
    count: 2
    accelerator_type: v5litepod-8
    runtime_version: tpu-vm-base
    resources: {CPU: 8, TPU: 8}
    spot: true
  v5e_16:
    count: 1
    accelerator_type: v5litepod-16
    runtime_version: tpu-vm-base
    resources: {CPU: 16, TPU: 16}
"""


@pytest.fixture
def cfg(tmp_path):
    p = tmp_path / "cluster.yaml"
    p.write_text(CONFIG_YAML)
    return load_config(str(p))


def _launcher(cfg, tmp_path, api):
    return ClusterLauncher(cfg, transport=api,
                           state_path=str(tmp_path / "state.json"))


def test_load_config_validates(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("cluster_name: x\n")
    with pytest.raises(ValueError):
        load_config(str(p))


def test_up_creates_configured_counts(cfg, tmp_path):
    api = FakeTpuApi(delay_polls=0)
    launcher = _launcher(cfg, tmp_path, api)
    created = launcher.up()
    assert len(created) == 3  # 2x v5e_8 + 1x v5e_16
    posts = [u for m, u in api.calls if m == "POST"]
    assert len(posts) == 3
    types = sorted(launcher.provider._nodes[p]["node_type"] for p in created)
    assert types == ["v5e_16", "v5e_8", "v5e_8"]
    # idempotent: a second up with the fleet live creates nothing
    assert launcher.up() == []


def test_status_reports_qr_states(cfg, tmp_path):
    api = FakeTpuApi(delay_polls=0)
    launcher = _launcher(cfg, tmp_path, api)
    launcher.up()
    rows = launcher.status()
    assert len(rows) == 3
    assert all(r["state"] in ("WAITING_FOR_RESOURCES", "ACTIVE")
               for r in rows)
    assert {r["node_type"] for r in rows} == {"v5e_8", "v5e_16"}


def test_down_from_fresh_process_via_state_file(cfg, tmp_path):
    """`raytpu down` runs in a NEW process: the state file must carry the
    fleet so teardown terminates exactly what up launched."""
    api = FakeTpuApi(delay_polls=0)
    created = _launcher(cfg, tmp_path, api).up()
    state = json.loads((tmp_path / "state.json").read_text())
    assert set(state["nodes"]) == set(created)
    # fresh launcher (new "process"), same state file + fake API
    launcher2 = _launcher(cfg, tmp_path, api)
    assert set(launcher2.provider._nodes) == set(created)
    torn = launcher2.down()
    assert set(torn) == set(created)
    assert api.qrs == {}  # every QR got its DELETE
    assert launcher2.status() == [] or all(
        r["state"] not in ("ACTIVE", "WAITING_FOR_RESOURCES")
        for r in launcher2.status())


def test_up_wait_blocks_until_active(cfg, tmp_path):
    api = FakeTpuApi(delay_polls=1)
    launcher = _launcher(cfg, tmp_path, api)
    launcher.up(wait=True, wait_timeout_s=10)
    assert all(r["state"] == "ACTIVE" for r in launcher.status())


def test_default_state_path_is_per_cluster():
    assert default_state_path("a") != default_state_path("b")


def test_cli_wiring(cfg, tmp_path, monkeypatch, capsys):
    """`raytpu up/down/status --config` resolve to the launcher (argparse
    wiring smoke; the launcher itself is covered above)."""
    from ray_tpu.scripts import cli

    api = FakeTpuApi(delay_polls=0)

    class _PatchedLauncher(ClusterLauncher):
        def __init__(self, config, transport=None, state_path=None):
            super().__init__(config, transport=api, state_path=str(
                tmp_path / "cli-state.json"))

    monkeypatch.setattr("ray_tpu.autoscaler.launcher.ClusterLauncher",
                        _PatchedLauncher)
    cfg_path = str(tmp_path / "cluster.yaml")
    with open(cfg_path, "w") as f:
        f.write(CONFIG_YAML)
    cli.main(["up", "--config", cfg_path])
    out = capsys.readouterr().out
    assert out.count("created qr-") == 3
    cli.main(["status", "--config", cfg_path])
    out = capsys.readouterr().out
    assert "v5e_8" in out and "v5e_16" in out
    cli.main(["down", "--config", cfg_path])
    out = capsys.readouterr().out
    assert "3 node(s) torn down" in out
