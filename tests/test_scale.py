"""Scale-envelope + chaos tests.

Reference: ``release/benchmarks/README.md:27-31`` (many-tasks /
many-actors / many-PGs release envelope; the single-box CI analogue
pushes counts, not cluster size) and
``python/ray/tests/chaos/chaos_network_delay.yaml`` (inject link latency,
assert the cluster survives).  Every test also asserts the bookkeeping
drains: leaked refcounts / stream states / pending tables are exactly the
regressions these envelopes exist to catch.
"""

import gc
import os
import time

import pytest

import ray_tpu
from ray_tpu.util.procmem import PeakRssSampler, rss_mb


def _worker_tables():
    from ray_tpu.core.core_worker import global_worker
    w = global_worker()
    rc = w.reference_counter
    return {
        "pending_tasks": dict(w.task_manager.pending),
        "streams": dict(w.streams),
        "gen_emitters": dict(w._gen_emitters),
        "refs_local": {k: v for k, v in rc.local.items() if v},
        "refs_submitted": {k: v for k, v in rc.submitted.items() if v},
        "refs_borrowed": {k: v for k, v in rc.borrowers.items() if v},
    }


def _assert_tables_drain(timeout_s: float = 15.0):
    """All owner-side tables return to zero once refs are gone."""
    gc.collect()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        tables = _worker_tables()
        if not any(tables.values()):
            return
        time.sleep(0.2)
        gc.collect()
    leaked = {k: len(v) for k, v in _worker_tables().items() if v}
    assert not leaked, f"tables did not drain: {leaked}"


@pytest.mark.parametrize("depth", [
    pytest.param(10_000, id="10k", marks=pytest.mark.timeout(300)),
    pytest.param(100_000, id="100k",
                 marks=[pytest.mark.slow, pytest.mark.timeout(900)]),
])
def test_queued_tasks_drain(ray_start_regular, depth):
    """``depth`` tasks queue far beyond the CPUs and ALL complete, under an
    asserted peak-RSS ceiling (the admission gate + bounded event buffers
    keep owner memory flat in the queue depth), and every owner-side
    per-task table returns to its baseline (empty) size afterwards."""
    from ray_tpu.core.core_worker import global_worker

    @ray_tpu.remote
    def inc(x):
        return x + 1

    ray_tpu.get([inc.remote(0) for _ in range(8)])  # warm the pool
    gc.collect()
    rss0 = rss_mb()
    sampler = PeakRssSampler()
    refs = [inc.remote(i) for i in range(depth)]
    # Drain in chunks: completion order tracks submission order closely
    # enough that each get() chunk is mostly resolved already, and the
    # driver never parks 100k get-coroutines at once.
    total, count, first, last = 0, 0, None, None
    for i in range(0, depth, 10_000):
        chunk = ray_tpu.get(refs[i:i + 10_000], timeout=600)
        count += len(chunk)
        total += sum(chunk)
        if first is None:
            first = chunk[0]
        last = chunk[-1]
    peak = sampler.stop()
    assert count == depth
    assert first == 1 and last == depth
    assert total == depth * (depth + 1) // 2
    # Memory ceiling: flat base + a small per-task budget.  The budget is
    # generous (refs, result records, and event buffers all scale with
    # depth by design) — the assertion exists to catch the regression
    # class where retained-per-task state grows by an extra struct, not
    # to pin exact allocator behavior.
    ceiling_mb = 300.0 + depth * 0.004
    assert peak - rss0 < ceiling_mb, (
        f"peak RSS grew {peak - rss0:.0f} MB over a {depth}-task drain "
        f"(ceiling {ceiling_mb:.0f} MB)")
    w = global_worker()
    assert w.admission_gate.inflight == 0
    # the bounded owner event buffer never exceeded its cap
    from ray_tpu.core.config import get_config
    assert len(w._task_events) <= get_config().task_events_max_buffer
    del refs, chunk
    _assert_tables_drain()


@pytest.mark.timeout(600)
@pytest.mark.slow
def test_500_actors_register(ray_start_regular):
    """500 actors register with the GCS and answer a call (waves of 50 so
    the 1-core box never hosts more than 50 worker processes at once —
    the reference envelope runs `many_actors` on a real cluster)."""
    from ray_tpu.util.state import list_actors

    @ray_tpu.remote(num_cpus=0)
    class A:
        def pid(self):
            return os.getpid()

    total, wave = 500, 50
    seen_pids = set()
    for w in range(total // wave):
        actors = [A.remote() for _ in range(wave)]
        pids = ray_tpu.get([a.pid.remote() for a in actors], timeout=240)
        seen_pids.update(pids)
        for a in actors:
            ray_tpu.kill(a)
    assert len(seen_pids) == total  # every actor had its own process
    rows = list_actors(limit=2000)
    assert len(rows) >= total
    alive = [r for r in rows if r.get("state") == "ALIVE"]
    assert not alive, f"{len(alive)} actors still alive after kill"
    _assert_tables_drain()


@pytest.mark.timeout(300)
def test_100_placement_groups_cycle(ray_start_regular):
    """100 PGs schedule concurrently, all become ready, all remove; agent
    resources return to the starting level and the GCS table empties."""
    from ray_tpu.util.state import list_placement_groups

    start_cpu = ray_tpu.available_resources().get("CPU", 0)
    pgs = [ray_tpu.placement_group([{"CPU": 0.01}]) for _ in range(100)]
    assert all(pg.ready(timeout=60) for pg in pgs)
    assert len(list_placement_groups(limit=1000)) >= 100
    for pg in pgs:
        ray_tpu.remove_placement_group(pg)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if (not list_placement_groups(limit=1000)
                and abs(ray_tpu.available_resources().get("CPU", 0)
                        - start_cpu) < 1e-6):
            break
        time.sleep(0.2)
    assert not list_placement_groups(limit=1000)
    assert abs(ray_tpu.available_resources().get("CPU", 0)
               - start_cpu) < 1e-6
    _assert_tables_drain()


@pytest.mark.timeout(300)
def test_network_delay_chaos(ray_start_cluster):
    """200 ms on every RPC link via the seeded fault-injection plane
    (RAYTPU_CHAOS_SPEC — the driver AND the agent subprocesses inherit
    it): tasks, actors, and cross-node health checking all survive — the
    chaos_network_delay.yaml analogue, now on core/chaos.py's injector."""
    import json

    from ray_tpu.utils.testing import CPU_WORKER_ENV
    from ray_tpu.util.state import list_nodes

    cluster = ray_start_cluster
    spec = json.dumps({"seed": 0,
                       "rules": [{"kind": "delay", "ms": 200}]})
    os.environ["RAYTPU_CHAOS_SPEC"] = spec
    try:
        cluster.add_node(num_cpus=2)
        cluster.add_node(num_cpus=2)
        cluster.wait_for_nodes(2, timeout=60)
        env = dict(CPU_WORKER_ENV)
        env["RAYTPU_CHAOS_SPEC"] = spec
        ray_tpu.init(address=cluster.address, worker_env=env,
                     _system_config={"chaos_spec": spec})

        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get(f.remote(21), timeout=120) == 42

        @ray_tpu.remote
        class C:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        c = C.remote()
        assert ray_tpu.get([c.bump.remote() for _ in range(3)],
                           timeout=120) == [1, 2, 3]

        # laggy heartbeats must NOT trip the failure detector: the links
        # are slow (0.2 s << period 1 s x threshold 5), not dead
        time.sleep(8)
        nodes = list_nodes()
        assert sum(1 for n in nodes if n.get("alive")) == 2, nodes
        # the injector observably carried the delays in this process
        from ray_tpu.core import chaos
        inj = chaos.injector()
        assert inj is not None
        assert inj.injected_counts().get("delay", 0) > 0
    finally:
        os.environ.pop("RAYTPU_CHAOS_SPEC", None)
