"""Job submission + CLI tests (reference: dashboard/modules/job/tests,
python/ray/tests/test_cli.py)."""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_job_submission_end_to_end(ray_start_regular, tmp_path):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    script = tmp_path / "entry.py"
    script.write_text(textwrap.dedent("""
        import os
        print("hello from job", os.environ.get("RAYTPU_JOB_ID"))
        print("MARKER_OK")
    """))
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} {script}",
        metadata={"who": "test"})
    status = client.wait_until_finish(job_id, timeout=120)
    assert status == "SUCCEEDED"
    logs = client.get_job_logs(job_id)
    assert "MARKER_OK" in logs
    assert job_id in logs
    infos = client.list_jobs()
    assert any(j["job_id"] == job_id and j["metadata"]["who"] == "test"
               for j in infos)


def test_job_failure_and_stop(ray_start_regular, tmp_path):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    bad = client.submit_job(entrypoint=f"{sys.executable} -c 'exit(3)'")
    assert client.wait_until_finish(bad, timeout=60) == "FAILED"
    assert client.get_job_info(bad)["exit_code"] == 3

    slow = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
    time.sleep(1)
    client.stop_job(slow)
    assert client.get_job_status(slow) == "STOPPED"


def test_job_working_dir(ray_start_regular, tmp_path):
    from ray_tpu.job import JobSubmissionClient

    wd = tmp_path / "app"
    wd.mkdir()
    (wd / "main.py").write_text("print(open('data.txt').read())")
    (wd / "data.txt").write_text("WORKDIR_PAYLOAD")
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} main.py",
        runtime_env={"working_dir": str(wd)})
    assert client.wait_until_finish(job_id, timeout=120) == "SUCCEEDED"
    assert "WORKDIR_PAYLOAD" in client.get_job_logs(job_id)


def test_job_driver_joins_cluster(ray_start_regular, tmp_path):
    """The submitted entrypoint connects back to this cluster and runs a
    task (the reference's driver-on-cluster contract)."""
    from ray_tpu.job import JobSubmissionClient

    script = tmp_path / "driver.py"
    script.write_text(textwrap.dedent("""
        import ray_tpu
        ray_tpu.init(address="auto")

        @ray_tpu.remote
        def f(x):
            return x * 2

        print("RESULT", ray_tpu.get(f.remote(21)))
        ray_tpu.shutdown()
    """))
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"{sys.executable} {script}")
    assert client.wait_until_finish(job_id, timeout=180) == "SUCCEEDED"
    assert "RESULT 42" in client.get_job_logs(job_id)


@pytest.mark.slow
def test_cli_start_status_submit_stop(tmp_path):
    """Full daemon lifecycle through the CLI binary."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cli = [sys.executable, "-m", "ray_tpu.scripts.cli"]

    def run(*args, timeout=120):
        return subprocess.run(cli + list(args), capture_output=True,
                              text=True, env=env, cwd=REPO, timeout=timeout)

    if os.path.exists("/tmp/raytpu/head.json"):
        run("stop")
    r = run("start", "--head", "--num-cpus", "4")
    assert r.returncode == 0, r.stderr
    assert "head started" in r.stdout
    try:
        r = run("status")
        assert r.returncode == 0, r.stderr
        assert "node(s)" in r.stdout
        script = tmp_path / "ok.py"
        script.write_text("print('CLI_JOB_OK')")
        r = run("submit", "--", sys.executable, str(script))
        assert r.returncode == 0, r.stderr + r.stdout
        assert "CLI_JOB_OK" in r.stdout
        assert "SUCCEEDED" in r.stdout
    finally:
        r = run("stop")
        assert r.returncode == 0, r.stderr
    assert not os.path.exists("/tmp/raytpu/head.json")


def test_runtime_env_py_modules(tmp_path):
    """init(runtime_env=py_modules) ships a real package to workers: tasks
    import it even though it exists nowhere on the workers' sys.path
    (reference: runtime_env packaging via GCS)."""
    import ray_tpu
    from ray_tpu.utils.testing import CPU_WORKER_ENV

    pkg = tmp_path / "shipped_pkg"
    (pkg / "shipped_pkg").mkdir(parents=True)
    (pkg / "shipped_pkg" / "__init__.py").write_text(
        "MAGIC = 'runtime-env-works'\n")

    ray_tpu.init(num_cpus=2,
                 runtime_env={"py_modules": [str(pkg / "shipped_pkg")],
                              "env_vars": {"SHIPPED_FLAG": "yes"}},
                 worker_env=dict(CPU_WORKER_ENV))
    try:
        @ray_tpu.remote
        def use_pkg():
            import os
            import shipped_pkg
            return shipped_pkg.MAGIC, os.environ.get("SHIPPED_FLAG")

        magic, flag = ray_tpu.get(use_pkg.remote(), timeout=60)
        assert magic == "runtime-env-works"
        assert flag == "yes"
    finally:
        ray_tpu.shutdown()
