"""Torch backend: gloo process group across worker actors + DDP wrap
(reference: ``python/ray/train/tests/test_torch_trainer.py``)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")


def _torch_loop(config):
    import torch
    import torch.distributed as dist

    from ray_tpu import train
    from ray_tpu.train.backend import prepare_torch_model

    assert dist.is_initialized()
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == 2

    # allreduce sanity: sum of ranks
    t = torch.tensor([float(rank + 1)])
    dist.all_reduce(t)
    assert t.item() == 3.0

    # tiny DDP regression: y = 2x, both ranks see different shards
    torch.manual_seed(0)
    model = prepare_torch_model(torch.nn.Linear(1, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    # [-1, 1] inputs keep SGD at lr=0.1 stable (mean(x^2) ~ 0.4, so the
    # quadratic's curvature is well inside the step-size bound).
    xs = torch.linspace(-1, 1, 8).reshape(-1, 1)[rank::2]
    ys = 2 * xs
    for _ in range(200):
        opt.zero_grad()
        loss = ((model(xs) - ys) ** 2).mean()
        loss.backward()  # DDP allreduces grads here
        opt.step()
    w = (model.module if hasattr(model, "module") else model).weight.item()
    train.report({"w": w, "loss": float(loss.item()), "rank": rank})


def test_torch_backend_ddp(ray_start_regular):
    from ray_tpu.train import DataParallelTrainer, ScalingConfig
    from ray_tpu.train.backend import TorchBackendConfig

    trainer = DataParallelTrainer(
        train_loop_per_worker=_torch_loop,
        backend_config=TorchBackendConfig(backend="gloo"),
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    assert abs(result.metrics["w"] - 2.0) < 0.1
    assert result.metrics["loss"] < 0.05
