"""Conv (Atari-capable) model path + SAC (reference:
rllib/models/torch/visionnet.py, rllib/algorithms/sac)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("gymnasium")

import ray_tpu  # noqa: E402


def test_conv_model_forward_and_grad():
    import jax.numpy as jnp

    from ray_tpu.rllib.conv import ActorCriticConv

    # Atari-shaped: 84x84x4 stacked frames, Nature filters
    model = ActorCriticConv(obs_shape=(84, 84, 4), action_dim=6)
    params = model.init(jax.random.PRNGKey(0))
    obs = jnp.zeros((3, 84, 84, 4), jnp.uint8)
    pi, v = model.apply(params, obs)
    assert pi.shape == (3, 6) and v.shape == (3,)

    def loss(p):
        pi, v = model.apply(p, obs.astype(jnp.float32))
        return (pi ** 2).mean() + (v ** 2).mean()

    grads = jax.grad(loss)(params)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in
               jax.tree_util.tree_leaves(grads))


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_ppo_conv_learns_catch(ray_start_regular):
    """Pixel-observation learning smoke: the conv torso must beat the
    random policy (~-0.8 mean return) decisively on the Catch env."""
    from ray_tpu.rllib.ppo import PPOConfig
    from ray_tpu.utils.testing import CPU_WORKER_ENV

    algo = (PPOConfig()
            .environment("ray_tpu.rllib.examples_env:Catch-v0")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=64)
            .training(lr=3e-4,
                      model=dict(conv=True, filters=((16, 4, 2), (32, 3, 1)),
                                 conv_hidden=128),
                      entropy_coeff=0.01)
            .debugging(seed=0, worker_env=dict(CPU_WORKER_ENV))
            .build())
    try:
        best = -9.0
        for _ in range(80):
            r = algo.train()
            erm = r["episode_return_mean"]
            if np.isfinite(erm):
                best = max(best, erm)
            if best >= 0.5:
                break
        assert best >= 0.5, f"conv PPO failed to learn Catch: best={best}"
    finally:
        algo.stop()


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_sac_learns_pendulum(ray_start_regular):
    """SAC on Pendulum-v1: random policy sits near -1400; learning must
    pull the 100-episode mean above -750 within ~10k env steps."""
    from ray_tpu.rllib.sac import SACConfig

    algo = (SACConfig()
            .environment("Pendulum-v1")
            .env_runners(rollout_steps=200)
            .training(batch_size=128, train_iters=200,
                      replay=dict(capacity=50_000, learn_starts=600))
            .debugging(seed=0)
            .build())
    try:
        best = -1e9
        for _ in range(50):
            r = algo.train()
            erm = r["episode_return_mean"]
            if np.isfinite(erm):
                best = max(best, erm)
            if best > -750.0:
                break
        assert best > -750.0, f"SAC failed to learn Pendulum: best={best}"
        assert np.isfinite(r["critic_loss"]) and np.isfinite(r["alpha"])
    finally:
        algo.stop()


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_td3_learns_pendulum(ray_start_regular):
    """TD3 on Pendulum-v1, same gate as SAC: 100-episode mean above -750
    (random sits near -1400).  Exercises clipped double-Q targets, target
    policy smoothing, and delayed actor updates."""
    from ray_tpu.rllib import TD3Config

    algo = (TD3Config()
            .environment("Pendulum-v1")
            .env_runners(rollout_steps=200)
            .training(batch_size=128, train_iters=200,
                      replay=dict(capacity=50_000, learn_starts=600))
            .debugging(seed=0)
            .build())
    try:
        best = -1e9
        for _ in range(50):
            r = algo.train()
            erm = r["episode_return_mean"]
            if np.isfinite(erm):
                best = max(best, erm)
            if best > -750.0:
                break
        assert best > -750.0, f"TD3 failed to learn Pendulum: best={best}"
        assert np.isfinite(r["critic_loss"])
        # the delayed actor did step (loss left its 0 initialization)
        assert r["actor_loss"] != 0.0
    finally:
        algo.stop()


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_ddpg_learns_pendulum(ray_start_regular):
    """DDPG (TD3 minus twin critics / smoothing / delay — reference:
    rllib/algorithms/ddpg) clears the same Pendulum gate; its update
    runs the single-critic branch of the jitted TD3 program."""
    from ray_tpu.rllib import DDPGConfig

    config = (DDPGConfig()
              .environment("Pendulum-v1")
              .env_runners(rollout_steps=200)
              .training(batch_size=128, train_iters=200,
                        replay=dict(capacity=50_000, learn_starts=600))
              .debugging(seed=0))
    assert config.train["twin_q"] is False
    assert config.train["policy_delay"] == 1
    algo = config.build()
    try:
        best = -1e9
        for _ in range(50):
            r = algo.train()
            erm = r["episode_return_mean"]
            if np.isfinite(erm):
                best = max(best, erm)
            if best > -750.0:
                break
        assert best > -750.0, f"DDPG failed to learn Pendulum: best={best}"
        assert np.isfinite(r["critic_loss"]) and r["actor_loss"] != 0.0
    finally:
        algo.stop()
