"""Cluster metrics-history store tests (ISSUE 10 satellites): ring-buffer
age-out, counter->rate derivation across resets, Prometheus parsing, and
the dashboard /api/metrics rework (freshest-sample serving + explicit
{"error": ...} entries for unreachable nodes)."""

import pytest

from ray_tpu.dashboard.history import (MetricsHistory, find_one,
                                       find_samples, parse_prometheus)


def test_parse_prometheus_types_and_samples():
    text = "\n".join([
        "# HELP raytpu_x_total things",
        "# TYPE raytpu_x_total counter",
        'raytpu_x_total{node="n1"} 5',
        "# TYPE raytpu_h_seconds histogram",
        'raytpu_h_seconds_bucket{le="+Inf"} 3',
        'raytpu_h_seconds_sum 0.5',
        'raytpu_h_seconds_count 3',
        "# TYPE raytpu_g gauge",
        "raytpu_g 7.5",
        "garbage line without number x",
        "",
    ])
    samples, counters = parse_prometheus(text)
    assert samples['raytpu_x_total{node="n1"}'] == 5.0
    assert samples["raytpu_g"] == 7.5
    assert samples["raytpu_h_seconds_count"] == 3.0
    # counters/histograms classified; gauges not
    assert "raytpu_x_total" in counters
    assert "raytpu_h_seconds" in counters
    assert "raytpu_g" not in counters
    # the malformed line is skipped, not fatal
    assert "garbage" not in " ".join(samples)


def test_ring_buffer_age_out_and_count_bound():
    st = MetricsHistory(window_s=10.0, period_s=1.0)
    for i in range(30):
        st.add_sample("n1", {"raytpu_g": float(i)}, ts=100.0 + i)
    ts, latest = st.latest()
    assert ts == 129.0 and latest["n1"]["raytpu_g"] == 29.0
    series = st.series("n1")["raytpu_g"]
    # age-out: only the 10 s window survives (and the deque maxlen holds)
    assert all(t >= 129.0 - 10.0 for t, _v in series)
    assert 2 <= len(series) <= 12
    # an idle node's buffer ages out relative to ITS OWN appends only;
    # a fresh node doesn't disturb it
    st.add_sample("n2", {"raytpu_g": 1.0}, ts=500.0)
    assert st.series("n1")["raytpu_g"]


def test_rates_and_counter_reset():
    st = MetricsHistory(window_s=100.0, period_s=1.0)
    st.add_sample("n1", {"raytpu_req_total": 10.0, "raytpu_g": 5.0},
                  counters={"raytpu_req_total"}, ts=100.0)
    st.add_sample("n1", {"raytpu_req_total": 30.0, "raytpu_g": 6.0},
                  ts=102.0)
    # counter: (30-10)/2 = 10/s; the gauge derives NO rate
    rates = st.rates("n1")
    assert rates["raytpu_req_total"] == [[102.0, 10.0]]
    assert "raytpu_g" not in rates
    # counter RESET (process restart): value drops -> rate = new/dt, not
    # a bogus negative
    st.add_sample("n1", {"raytpu_req_total": 4.0}, ts=104.0)
    assert st.rates("n1")["raytpu_req_total"][-1] == [104.0, 2.0]
    # histogram suffixes rate too (classified via the base name)
    st.add_sample("n2", {"raytpu_h_seconds_count": 2.0},
                  counters={"raytpu_h_seconds"}, ts=10.0)
    st.add_sample("n2", {"raytpu_h_seconds_count": 6.0}, ts=12.0)
    assert st.rates("n2")["raytpu_h_seconds_count"] == [[12.0, 2.0]]


def test_error_samples_break_rate_chain_and_surface_in_latest():
    st = MetricsHistory(window_s=100.0, period_s=1.0)
    st.add_sample("n1", {"raytpu_req_total": 10.0},
                  counters={"raytpu_req_total"}, ts=100.0)
    st.record_error("n1", "ConnectionRefusedError: boom", ts=102.0)
    st.add_sample("n1", {"raytpu_req_total": 50.0}, ts=104.0)
    # latest() after a recovery serves the good sample again
    _ts, latest = st.latest()
    assert latest["n1"]["raytpu_req_total"] == 50.0
    # but NO rate spans the scrape gap (the 10 -> 50 delta includes an
    # unknown amount of downtime)
    assert "raytpu_req_total" not in st.rates("n1")
    # a node whose LAST sample errored reports the error explicitly
    st.record_error("n1", "timeout", ts=106.0)
    _ts, latest = st.latest()
    assert latest["n1"] == {"error": "timeout"}
    assert st.summary("n1")["error"] == "timeout"


def test_find_helpers():
    samples = {
        'raytpu_resource_total{node="ab",reporter="r",resource="CPU"}': 8.0,
        'raytpu_resource_total{node="ab",reporter="r",resource="TPU"}': 4.0,
        "raytpu_plain": 1.0,
    }
    assert find_samples(samples, "raytpu_resource_total",
                        resource="CPU") == [8.0]
    assert find_one(samples, "raytpu_resource_total", node="ab") == 8.0
    assert find_one(samples, "raytpu_plain") == 1.0
    assert find_one(samples, "raytpu_missing", default=-1) == -1


def test_dashboard_scrape_records_unreachable_nodes(monkeypatch):
    """The /api/metrics rework satellite: a node that is alive but whose
    /metrics cannot be scraped (or that advertises no metrics_port) must
    land in the store as an explicit {"error": ...} entry, not silently
    vanish from the response."""
    pytest.importorskip("aiohttp")
    import asyncio

    from ray_tpu.dashboard.head import DashboardHead
    from ray_tpu.util import state

    rows = [
        {"node_id": "a" * 24, "alive": True, "address": "127.0.0.1:1",
         "labels": {"metrics_port": "1"}},      # nothing listens on :1
        {"node_id": "b" * 24, "alive": True, "address": "127.0.0.1:2",
         "labels": {}},                          # no metrics_port at all
        {"node_id": "c" * 24, "alive": False, "address": "127.0.0.1:3",
         "labels": {"metrics_port": "9"}},       # dead: skipped entirely
    ]
    monkeypatch.setattr(state, "list_nodes", lambda *a, **k: rows)
    head = DashboardHead()
    loop = asyncio.new_event_loop()
    loop.run_until_complete(head._scrape_once())
    _ts, latest = head.history.latest()
    assert "error" in latest["a" * 12]
    assert latest["b" * 12] == {"error": "no metrics_port advertised"}
    assert "c" * 12 not in latest
    # a node that DIES must drop from the store on the next pass — its
    # last sample must not keep serving as live data
    rows[0]["alive"] = False
    loop.run_until_complete(head._scrape_once())
    _ts, latest = head.history.latest()
    assert "a" * 12 not in latest
    assert "b" * 12 in latest


def test_rejoin_after_dark_gap_drops_stale_tail():
    """A node that errored out and rejoined past stale_after_s must not
    serve its pre-outage samples as history: the good tail is purged
    (error markers stay — they are the flap evidence), and rates
    re-chain from the fresh incarnation only."""
    st = MetricsHistory(window_s=1000.0, period_s=1.0, stale_after_s=15.0)
    st.add_sample("n1", {"raytpu_req_total": 10.0},
                  counters={"raytpu_req_total"}, ts=100.0)
    st.add_sample("n1", {"raytpu_req_total": 20.0}, ts=101.0)
    st.record_error("n1", "heartbeat timeout", ts=103.0)
    # dark for 100s >> stale_after_s, then the node comes back
    st.add_sample("n1", {"raytpu_req_total": 5.0}, ts=203.0)
    st.add_sample("n1", {"raytpu_req_total": 9.0}, ts=204.0)
    _ts, latest = st.latest()
    assert latest["n1"]["raytpu_req_total"] == 9.0
    # rates span ONLY the new incarnation (one 203->204 delta) — the
    # stale 100/101s tail is gone, so no rate bridges the outage
    pts = st.rates("n1")["raytpu_req_total"]
    assert len(pts) == 1 and pts[0][0] == 204.0 and pts[0][1] == 4.0
    # within stale_after_s the tail is NOT purged (normal scrape cadence)
    st.add_sample("n1", {"raytpu_req_total": 12.0}, ts=206.0)
    assert len(st.rates("n1")["raytpu_req_total"]) == 2


def test_flaps_counts_recoveries_in_window():
    st = MetricsHistory(window_s=1000.0, period_s=1.0, stale_after_s=1e9)
    assert st.flaps("ghost") == 0
    st.add_sample("n1", {"raytpu_g": 1.0}, ts=100.0)
    st.record_error("n1", "boom", ts=101.0)
    st.add_sample("n1", {"raytpu_g": 1.0}, ts=102.0)   # flap 1
    st.record_error("n1", "boom", ts=103.0)
    st.record_error("n1", "boom", ts=104.0)            # still down: no flap
    st.add_sample("n1", {"raytpu_g": 1.0}, ts=105.0)   # flap 2
    assert st.flaps("n1", now=110.0) == 2
    # a narrow window only sees the second recovery
    assert st.flaps("n1", window_s=6.0, now=110.0) == 1
    st.forget("n1")
    assert st.flaps("n1", now=110.0) == 0
