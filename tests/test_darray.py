"""Distributed block arrays (reference:
``python/ray/experimental/array/distributed/core.py`` + its tests):
scatter/assemble roundtrip, block-task constructors, elementwise ops,
blocked matmul, and the TPU-side ``to_jax`` mesh bridge."""

import numpy as np
import pytest

from ray_tpu.experimental import darray


def test_from_numpy_roundtrip(ray_start_regular):
    a = np.arange(7 * 5, dtype=np.float32).reshape(7, 5)
    d = darray.from_numpy(a, block=3)  # ragged edge blocks
    assert d.num_blocks == (3, 2)
    assert d.block_shape == (3, 3)
    np.testing.assert_array_equal(d.assemble(), a)


def test_constructors(ray_start_regular):
    z = darray.zeros((5, 4), block=2)
    np.testing.assert_array_equal(z.assemble(), np.zeros((5, 4)))
    o = darray.ones((3, 3), block=2)
    np.testing.assert_array_equal(o.assemble(), np.ones((3, 3)))
    e = darray.eye(5, block=2)
    np.testing.assert_array_equal(e.assemble(), np.eye(5))


def test_elementwise_and_map(ray_start_regular):
    a = np.random.default_rng(0).standard_normal((6, 6)).astype(np.float32)
    b = np.random.default_rng(1).standard_normal((6, 6)).astype(np.float32)
    da, db = darray.from_numpy(a, block=4), darray.from_numpy(b, block=4)
    np.testing.assert_allclose((da + db).assemble(), a + b, rtol=1e-6)
    np.testing.assert_allclose((da * db).assemble(), a * b, rtol=1e-6)
    np.testing.assert_allclose(
        da.map_blocks(lambda x: x ** 2).assemble(), a ** 2, rtol=1e-6)


def test_blocked_dot(ray_start_regular):
    rng = np.random.default_rng(2)
    a = rng.standard_normal((9, 7)).astype(np.float32)
    b = rng.standard_normal((7, 8)).astype(np.float32)
    da = darray.from_numpy(a, block=4)
    db = darray.from_numpy(b, block=4)
    c = darray.dot(da, db)
    assert c.shape == (9, 8)
    np.testing.assert_allclose(c.assemble(), a @ b, rtol=1e-4, atol=1e-5)


def test_dot_validates(ray_start_regular):
    a = darray.zeros((4, 4), block=2)
    b = darray.zeros((6, 4), block=2)
    with pytest.raises(ValueError, match="inner dims"):
        darray.dot(a, b)


def test_to_jax_sharded(ray_start_regular, cpu_mesh_devices):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    a = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    d = darray.from_numpy(a, block=4)
    mesh = Mesh(np.array(cpu_mesh_devices[:8]).reshape(8), ("dp",))
    arr = d.to_jax(mesh, P("dp", None))
    assert isinstance(arr, jax.Array)
    assert arr.shape == (16, 8)
    # actually laid out over the mesh: 8 shards of 2 rows each
    assert len(arr.addressable_shards) == 8
    assert arr.addressable_shards[0].data.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(arr), a)
    # and it feeds a pjit program directly
    out = jax.jit(lambda x: (x * 2).sum())(arr)
    assert float(out) == float(a.sum() * 2)
