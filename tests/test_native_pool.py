"""Native shm arena tests: the C++ allocator and its store integration
(reference analogue: plasma store/dlmalloc tests)."""

import os

import numpy as np
import pytest

import ray_tpu


def test_allocator_alloc_free_coalesce(tmp_path):
    pytest.importorskip("ctypes")
    from ray_tpu.native import ShmPool, load_shm_pool

    if load_shm_pool() is None:
        pytest.skip("g++ unavailable")
    p = ShmPool(str(tmp_path / "pool"), 1 << 20)
    try:
        a, b, c = p.alloc(1000), p.alloc(2000), p.alloc(3000)
        assert a == 0 and b > a and c > b
        assert p.used > 0
        # data roundtrip through the mapping
        p.view(b, 2000)[:7] = b"payload"
        assert bytes(p.view(b, 7)) == b"payload"
        # free middle -> hole reused
        p.free(b)
        assert p.alloc(1500) == b
        # free everything -> coalesces back to one block
        for off in (a, b, c):
            p.free(off)
        assert p.used == 0
        assert p.num_blocks == 1
        # whole-arena alloc then overflow
        assert p.alloc((1 << 20) - 64) == 0
        assert p.alloc(128) == -1
    finally:
        p.close()
    assert not os.path.exists(str(tmp_path / "pool"))


def test_allocator_fragmentation_recovery(tmp_path):
    from ray_tpu.native import ShmPool, load_shm_pool

    if load_shm_pool() is None:
        pytest.skip("g++ unavailable")
    p = ShmPool(str(tmp_path / "pool"), 1 << 20)
    try:
        # exactly fill the arena: 16 x 64K, no tail remainder
        offs = [p.alloc(64 * 1024) for _ in range(16)]
        assert all(o >= 0 for o in offs)
        assert p.alloc(64) == -1
        # free every other -> 8 isolated 64K holes
        for o in offs[::2]:
            p.free(o)
        assert p.alloc(96 * 1024) == -1  # no two holes are adjacent
        p.free(offs[1])  # offs[0]+offs[1]+offs[2] coalesce to 192K
        assert p.alloc(96 * 1024) >= 0
    finally:
        p.close()


def test_store_uses_pool_and_roundtrips(ray_start_regular):
    from ray_tpu.core.api import _state
    from ray_tpu.native import load_shm_pool

    if load_shm_pool() is None:
        pytest.skip("g++ unavailable")
    store = _state.node_agent.store
    assert store.pool is not None, "native pool should be active"
    data = np.arange(2 * 1024 * 1024, dtype=np.uint8) % 199
    ref = ray_tpu.put(data)
    assert np.array_equal(ray_tpu.get(ref, timeout=60), data)

    @ray_tpu.remote
    def checksum(x):
        return int(x.astype(np.uint64).sum())

    # cross-process read through the pool-slice attach path
    assert ray_tpu.get(checksum.remote(ref), timeout=60) == \
        int(data.astype(np.uint64).sum())


def test_store_python_fallback(tmp_path):
    """The pure-Python file-per-object path still works when disabled."""
    import ray_tpu
    from ray_tpu.utils.testing import CPU_WORKER_ENV

    ray_tpu.init(num_cpus=2,
                 _system_config={"object_store_use_native_pool": False},
                 worker_env=dict(CPU_WORKER_ENV))
    try:
        from ray_tpu.core.api import _state
        assert _state.node_agent.store.pool is None
        data = np.ones(1024 * 1024, np.uint8)
        assert ray_tpu.get(ray_tpu.put(data), timeout=60).sum() == data.sum()
    finally:
        ray_tpu.shutdown()
