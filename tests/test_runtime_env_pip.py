"""pip/venv runtime-env isolation: two tasks with conflicting package
versions run side by side on one cluster (reference:
``python/ray/_private/runtime_env/pip.py`` + per-node ``uri_cache.py``)."""

import os
import zipfile

import pytest

import ray_tpu
from ray_tpu.utils.testing import CPU_WORKER_ENV


def _build_wheel(dist_dir: str, name: str, version: str, body: str) -> str:
    """Hand-roll a minimal wheel (a zip with code + dist-info) — no network,
    no build backend needed."""
    tag = f"{name}-{version}"
    path = os.path.join(dist_dir, f"{name}-{version}-py3-none-any.whl")
    meta = (f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n")
    wheel_meta = ("Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: "
                  "true\nTag: py3-none-any\n")
    with zipfile.ZipFile(path, "w") as z:
        z.writestr(f"{name}/__init__.py", body)
        z.writestr(f"{tag}.dist-info/METADATA", meta)
        z.writestr(f"{tag}.dist-info/WHEEL", wheel_meta)
        record = (f"{name}/__init__.py,,\n{tag}.dist-info/METADATA,,\n"
                  f"{tag}.dist-info/WHEEL,,\n{tag}.dist-info/RECORD,,\n")
        z.writestr(f"{tag}.dist-info/RECORD", record)
    return path


@pytest.mark.timeout(180)
def test_pip_env_failure_fails_task(tmp_path):
    """A pip env that cannot be built must FAIL the task with the real error
    (reference: RuntimeEnvSetupError) — not hang ray.get while the agent
    retries pip forever."""
    ray_tpu.init(num_cpus=2, worker_env=dict(CPU_WORKER_ENV))
    try:
        @ray_tpu.remote(runtime_env={
            "pip": ["definitely-not-a-package-xyz==9.9"],
            "pip_args": ["--no-index", "--find-links", str(tmp_path)]})
        def doomed():
            return 1

        with pytest.raises(Exception) as ei:
            ray_tpu.get(doomed.remote(), timeout=120)
        assert "pip install failed" in str(ei.value) or \
            "RuntimeEnvSetupError" in type(ei.value).__name__
    finally:
        ray_tpu.shutdown()


@pytest.mark.timeout(420)  # two venv builds on a slow box
def test_conflicting_pip_envs_one_cluster(tmp_path):
    wheels = str(tmp_path)
    _build_wheel(wheels, "confl", "1.0", "VERSION = '1.0'\n")
    _build_wheel(wheels, "confl", "2.0", "VERSION = '2.0'\n")
    pip_args = ["--no-index", "--find-links", wheels]

    ray_tpu.init(num_cpus=4, worker_env=dict(CPU_WORKER_ENV))
    try:
        @ray_tpu.remote(runtime_env={"pip": ["confl==1.0"],
                                     "pip_args": pip_args})
        def v1():
            import confl
            return confl.VERSION

        @ray_tpu.remote(runtime_env={"pip": ["confl==2.0"],
                                     "pip_args": pip_args})
        def v2():
            import confl
            return confl.VERSION

        @ray_tpu.remote
        def plain():
            import importlib.util
            return importlib.util.find_spec("confl") is None

        r1, r2 = v1.remote(), v2.remote()
        assert ray_tpu.get([r1, r2], timeout=300) == ["1.0", "2.0"]
        # the default interpreter never sees either install
        assert ray_tpu.get(plain.remote(), timeout=60) is True
        # venv workers are cached per env hash: a second call reuses the env
        assert ray_tpu.get(v1.remote(), timeout=120) == "1.0"
    finally:
        ray_tpu.shutdown()
