"""Train library tests — mirrors reference ``python/ray/train/tests``
(worker group, session report/checkpoint protocol, trainer fit, failure
recovery from checkpoint)."""

import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (Checkpoint, CheckpointConfig, FailureConfig,
                           JaxTrainer, Result, RunConfig, ScalingConfig,
                           DataParallelTrainer)


def test_trainer_reports_metrics(ray_start_regular, tmp_path):
    def loop(config):
        ctx = train.get_context()
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank(),
                          "world_size": ctx.get_world_size()})

    trainer = DataParallelTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    assert result.metrics["step"] == 2
    assert result.metrics["rank"] == 0
    assert result.metrics["world_size"] == 2


def test_trainer_checkpoint_roundtrip(ray_start_regular, tmp_path):
    def loop(config):
        import json
        import tempfile
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt:
            with open(os.path.join(ckpt.path, "s.json")) as f:
                start = json.load(f)["step"] + 1
        for step in range(start, 2):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "s.json"), "w") as f:
                json.dump({"step": step}, f)
            train.report({"step": step}, checkpoint=Checkpoint(d))

    trainer = DataParallelTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t2", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.checkpoint is not None
    # checkpoint was registered into the run dir with indexed names
    assert "checkpoint_" in result.checkpoint.path
    # resume: a new trainer starting from the returned checkpoint sees step 1
    trainer2 = DataParallelTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t2b", storage_path=str(tmp_path)),
        resume_from_checkpoint=result.checkpoint)
    r2 = trainer2.fit()
    assert len(r2.metrics_history) == 0 or r2.metrics["step"] <= 1


def test_failure_recovery_restores_checkpoint(ray_start_regular, tmp_path):
    marker = str(tmp_path / "crashed_once")

    def loop(config):
        import json
        import tempfile
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt:
            with open(os.path.join(ckpt.path, "s.json")) as f:
                start = json.load(f)["step"] + 1
        for step in range(start, 4):
            if step == 2 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("injected failure at step 2")
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "s.json"), "w") as f:
                json.dump({"step": step}, f)
            train.report({"step": step}, checkpoint=Checkpoint(d))

    trainer = DataParallelTrainer(
        train_loop_per_worker=loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t3", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=2)))
    result = trainer.fit()
    assert result.error is None
    # crashed at step 2, restored from checkpoint step 1, finished steps 2,3
    assert result.metrics["step"] == 3
    assert os.path.exists(marker)


def test_failure_exhausts_retries(ray_start_regular, tmp_path):
    def loop(config):
        raise ValueError("always fails")

    trainer = DataParallelTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t4", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)))
    with pytest.raises(train.TrainingFailedError):
        trainer.fit()


def test_dataset_shard_ingest(ray_start_regular, tmp_path):
    import ray_tpu.data as rdata

    def loop(config):
        shard = train.get_dataset_shard("train")
        total = 0
        rows = 0
        for batch in shard.iter_batches(batch_size=8, batch_format="numpy"):
            total += int(batch["id"].sum())
            rows += len(batch["id"])
        train.report({"rows": rows, "total": total})

    ds = rdata.range(64)
    trainer = DataParallelTrainer(
        train_loop_per_worker=loop,
        datasets={"train": ds},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t5", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["rows"] == 32  # equal split of 64 over 2 workers


def test_checkpoint_manager_topk(tmp_path):
    from ray_tpu.train.checkpoint import CheckpointManager
    import tempfile
    mgr = CheckpointManager(
        CheckpointConfig(num_to_keep=2, checkpoint_score_attribute="acc"),
        str(tmp_path))
    for i, acc in enumerate([0.1, 0.9, 0.5, 0.2]):
        d = tempfile.mkdtemp()
        open(os.path.join(d, "x"), "w").close()
        mgr.register(Checkpoint(d), {"acc": acc})
    kept = sorted(os.listdir(tmp_path))
    # keeps best (acc=0.9) + latest (index 3); 2 dirs
    assert len(mgr.tracked) == 2
    assert mgr.best.get_metadata() == {} and "checkpoint_000001" in mgr.best.path


def test_jax_trainer_single_worker_mesh(ray_start_regular, tmp_path):
    """End-to-end: JaxTrainer runs a sharded train step on the worker's
    8-device CPU mesh (stands in for one TPU host's slice)."""
    def loop(config):
        from ray_tpu.utils.testing import force_cpu_devices
        force_cpu_devices(8)
        import jax.numpy as jnp
        from ray_tpu.models import tiny
        from ray_tpu.parallel import (init_sharded_state, make_optimizer,
                                      make_train_step)
        ctx = train.get_context()
        mesh = ctx.mesh()  # from ScalingConfig.mesh
        assert dict(mesh.shape)["fsdp"] == 4 and dict(mesh.shape)["tp"] == 2
        cfg = tiny(seq=32)
        opt = make_optimizer(total_steps=3)
        state, sh = init_sharded_state(cfg, mesh, opt)
        step = make_train_step(cfg, mesh, opt, sh)
        import numpy as np
        rng = np.random.default_rng(0)
        for i in range(2):
            batch = {"tokens": rng.integers(0, cfg.vocab_size,
                                            (8, 32)).astype(np.int32)}
            state, metrics = step(state, batch)
            train.report({"loss": float(metrics["total_loss"]),
                          "step": int(state.step)})

    trainer = JaxTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=1,
                                     mesh={"fsdp": 4, "tp": 2}),
        run_config=RunConfig(name="t6", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["loss"] > 0


@pytest.mark.timeout(300)
def test_jax_trainer_two_process_distributed(ray_start_regular, tmp_path):
    """The multi-controller seam (VERDICT r3 weak #4): TWO worker processes
    form one jax.distributed namespace (CPU backend), build a mesh spanning
    both, and run a sharded train step where each process feeds its local
    batch slice — the CI stand-in for a multi-host TPU pod."""
    def loop(config):
        import jax
        import numpy as np
        from ray_tpu.models import tiny
        from ray_tpu.parallel import (MeshSpec, init_sharded_state,
                                      make_optimizer, make_train_step)
        ctx = train.get_context()
        assert jax.process_count() == 2, jax.process_count()
        assert len(jax.devices()) == 16  # 2 procs x 8 virtual CPU devices
        # dp is the process dim (jax.devices() orders by process), fsdp the
        # within-process slice: the batch gradient psum crosses processes.
        mesh = MeshSpec(dp=2, fsdp=8).build(jax.devices())
        cfg = tiny(seq=32)
        opt = make_optimizer(total_steps=3)
        state, sh = init_sharded_state(cfg, mesh, opt)
        step = make_train_step(cfg, mesh, opt, sh)
        rng = np.random.default_rng(ctx.get_world_rank())
        for i in range(2):
            # per-process LOCAL half of the global 32-row batch
            batch = {"tokens": rng.integers(
                0, cfg.vocab_size, (16, 32)).astype(np.int32)}
            state, metrics = step(state, batch)
            train.report({"loss": float(metrics["total_loss"]),
                          "step": int(state.step),
                          "world": jax.process_count()})

    trainer = JaxTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t6b", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["world"] == 2
    assert result.metrics["loss"] > 0


def test_checkpoint_storage_uri(ray_start_regular, tmp_path):
    """storage_path as a pyarrow-filesystem URI: reported checkpoints upload
    through pyarrow.fs and restore transparently (reference:
    train/_internal/storage.py StorageContext)."""
    import os

    from ray_tpu import train as rt_train
    from ray_tpu.train import (Checkpoint, CheckpointConfig,
                               DataParallelTrainer, RunConfig, ScalingConfig)

    storage_uri = f"file://{tmp_path}/bucket"

    def loop(config):
        import tempfile
        ckpt = rt_train.get_checkpoint()
        start = 0
        if ckpt:
            with ckpt.as_directory() as d:
                start = int(open(os.path.join(d, "it.txt")).read()) + 1
        for i in range(start, 3):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "it.txt"), "w") as f:
                f.write(str(i))
            rt_train.report({"iter": i}, checkpoint=Checkpoint(d))

    trainer = DataParallelTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="uri_exp", storage_path=storage_uri,
                             checkpoint_config=CheckpointConfig(num_to_keep=2)))
    result = trainer.fit()
    assert result.metrics["iter"] == 2
    # the checkpoint lives on the URI filesystem and materializes locally
    assert result.checkpoint is not None
    assert result.checkpoint.uri is not None
    with result.checkpoint.as_directory() as d:
        assert open(os.path.join(d, "it.txt")).read() == "2"
    # retention pruned to 2 on the target filesystem
    ckpts = [p for p in os.listdir(str(tmp_path / "bucket" / "uri_exp"))
             if p.startswith("checkpoint_")]
    assert len(ckpts) == 2, ckpts
