"""Benchmark: sharded causal-LM train step, tokens/sec/chip + MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline semantics (BASELINE.json): the north star is >=70% of a reference H100's
tokens/sec/device on Llama-family pretrain.  Public H100 pretrain runs land around
40% MFU, so the device-neutral comparison is MFU-based:

    vs_baseline = (our MFU) / (0.70 * 0.40)

i.e. 1.0 == the 70%-of-H100 target, >1.0 beats it.  MFU is model FLOPs (6*N_active
+ attention) over the chip's peak bf16 FLOPs.
"""

from __future__ import annotations

import argparse
import json
import os
import time


# The MFU arithmetic lives in the package now (the runtime train
# observability plane shares it: ray_tpu/models/config.py).  Lazy wrapper,
# not a top-level import — the {"skipped": "no TPU"} paths must work in a
# bare environment where only a (possibly wedged) jax is importable.
def detect_peak_flops(device) -> float:
    from ray_tpu.models.config import detect_peak_flops as _detect
    return _detect(device)


def estimate_hbm_bytes(cfg, batch: int, seq: int, n_devices: int) -> float:
    """Per-device HBM for one train step (fsdp over n devices, remat on,
    chunked cross-entropy).

    Round 1 OOMed because the estimate was `params * 12 * 1.35`, which missed
    the f32 gradients, the hoisted bf16 casts of the stacked params, and the
    f32 logits.  This models what the round-1 HLO allocation dump actually
    showed:
      * train state: f32 params (4) + f32 grads (4, coexist with state under
        donation) + adam mu/nu (8)
      * bf16 param casts: XLA hoists the `.astype(bf16)` of the loop-invariant
        stacked weights out of the layer scan (+2)
      * activations: scan carry checkpointed per layer (L*B*S*H*2) + one
        layer's transient attention scores (B*NH*S^2*2) + qkv/mlp temps
      * chunked CE: one [B, chunk, V] f32 logits block (fwd + bwd)
    """
    p = cfg.num_params()
    state = p * (4 + 4 + 8 + 2) / n_devices
    h, L, nh = cfg.hidden_size, cfg.num_layers, cfg.num_heads
    b = max(1, batch // n_devices)  # batch sharded over dp/fsdp
    carry = L * b * seq * h * 2
    scores = b * nh * seq * seq * 2
    temps = 8 * b * seq * max(h, cfg.mlp_size) * 2
    ce_chunk = 2 * b * min(512, seq) * cfg.vocab_size * 4
    return (state + carry + scores + temps + ce_chunk) * 1.10


def pick_config(args, n_devices: int, hbm_bytes: float):
    from ray_tpu.models import config as mcfg
    if args.preset == "debug":
        return mcfg.tiny(), 8, 64
    if args.preset != "auto":
        cfg = mcfg.PRESETS[args.preset]()
        return cfg, args.batch, args.seq or min(cfg.max_seq_len, 2048)
    # auto: largest Llama-family bench config (and largest batch <= requested)
    # that fits the measured HBM under the memory model above.
    for name in ("llama3-8b", "llama-1b", "llama-400m", "gpt2-124m"):
        cfg_fn = mcfg.PRESETS[name]
        seq = args.seq or (2048 if name != "gpt2-124m" else 1024)
        # batch must stay divisible by the mesh's dp*fsdp extent (= n_devices
        # here) or device_put on the batch sharding fails.
        batch = max(args.batch, n_devices)
        batch -= batch % n_devices
        while batch >= n_devices:
            if estimate_hbm_bytes(cfg_fn(), batch, seq, n_devices) < hbm_bytes:
                return cfg_fn(max_seq_len=seq), batch, seq
            batch = batch // 2 - (batch // 2) % n_devices
    return mcfg.tiny(), 8, 64


def _devices_or_skip(jax, timeout_s: float,
                     metric: str = "train_tokens_per_sec_per_chip"):
    """jax.devices(), or emit a structured skip and exit 0.

    The BENCH_r05 failure mode was an rc=1 traceback when the TPU plugin
    registered but setup failed UNAVAILABLE; the plugin can also wedge for
    many minutes in its internal retry loop before raising.  Both cases
    mean "no TPU attached" — an environment fact, not a benchmark failure —
    so the harness gets one parseable JSON line and rc=0.  The probe runs
    in a daemon thread so a wedged backend init cannot hang the process
    past ``timeout_s``."""
    import threading

    box: dict = {}

    def _probe():
        try:
            box["devices"] = jax.devices()
        except Exception as e:  # RuntimeError("Unable to initialize backend")
            box["error"] = e

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in box:
        return box["devices"]
    err = box.get("error")
    print(json.dumps({
        "metric": metric,
        "skipped": "no TPU",
        "error": (str(err).splitlines()[0][:300] if err is not None
                  else f"backend init exceeded {timeout_s:.0f}s"),
    }), flush=True)
    # os._exit: a wedged plugin thread must not block interpreter teardown
    os._exit(0)


# ------------------------------------------------------- chipspeed (>=1B)

#: every (splash, quant, zero) combination, off-arm first
CHIPSPEED_ARMS = [(s, q, z) for s in (False, True) for q in (False, True)
                  for z in (False, True)]


def _arm_name(splash: bool, quant: bool, zero: bool) -> str:
    on = [n for n, f in (("splash", splash), ("quant", quant),
                         ("zero", zero)) if f]
    return "+".join(on) if on else "off"


def _run_chipspeed_arm(jax, devices, splash, quant, zero, args):
    from ray_tpu.models import config as mcfg
    from ray_tpu.parallel import (MeshSpec, OptimizerSpec,
                                  init_sharded_state, init_zero_state,
                                  make_train_step)
    n = len(devices)
    if args.preset == "debug":
        base, batch, seq = mcfg.tiny(), max(8, n), 64
    else:
        # the >=1B config ROADMAP item 2 names (llama_1b is ~1.2B params)
        base = mcfg.llama_1b()
        seq = args.seq or base.max_seq_len
        batch = max(args.batch, n)
    batch -= batch % n
    cfg = mcfg.TransformerConfig(
        **{**base.__dict__, "max_seq_len": seq,
           "attention_impl": "splash" if splash else "auto"})
    spec = OptimizerSpec(total_steps=max(args.steps + args.warmup, 10))
    # quant/zero schedule their own dp collectives; the off arms keep
    # today's fsdp-sharded auto path exactly
    mesh = (MeshSpec(dp=-1, fsdp=1) if (quant or zero)
            else MeshSpec(fsdp=-1)).build(devices)
    remat = None if args.remat in ("none", "None") else args.remat

    t0 = time.time()
    if zero:
        state, sh = init_zero_state(cfg, mesh, spec)
    else:
        state, sh = init_sharded_state(cfg, mesh, spec.build())
    step = make_train_step(cfg, mesh, spec.build(), sh, remat=remat,
                           grad_quant_enabled=quant,
                           zero_sharded_update=zero, opt_spec=spec)
    toks = jax.random.randint(jax.random.PRNGKey(0), (batch, seq + 1), 0,
                              cfg.vocab_size)
    batch_dict = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, batch_dict)
    float(metrics["loss"])  # force (relay-safe host read)
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(args.steps):
        state, metrics = step(state, batch_dict)
    final_loss = float(metrics["loss"])
    dt = time.time() - t0
    memory = None
    try:
        ms = devices[0].memory_stats() or {}
        memory = {k: int(ms[k]) for k in
                  ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                  if k in ms} or None
    except Exception:
        pass
    tok_chip = batch * seq * args.steps / dt / n
    mfu = tok_chip * cfg.flops_per_token(seq) / detect_peak_flops(devices[0])
    return {
        "mfu": round(mfu, 4),
        "tokens_per_sec_per_chip": round(tok_chip, 2),
        "step_ms": round(dt / args.steps * 1000, 1),
        "compile_s": round(compile_s, 1),
        "loss": round(final_loss, 4),
        "memory": memory,
        "wire_bytes_per_step": {f"{op}/{wd}": v for (op, wd), v
                                in step.collective_bytes.items()},
        "opt_state_bytes": step.opt_state_bytes,
        "model": f"{cfg.num_params() / 1e6:.0f}M",
        "batch": batch, "seq": seq, "n_devices": n,
    }


def run_chipspeed(args, jax):
    """The >=1B arm matrix: (splash, quant, zero) x {on, off}, per-phase
    checkpointing (the bench_llm pattern — a dying tunnel loses nothing),
    one final JSON line + BENCH_CHIPSPEED.json."""
    metric = "chipspeed_1b_mfu"
    devices = _devices_or_skip(jax, timeout_s=args.backend_timeout,
                               metric=metric)
    if devices[0].platform == "cpu" and args.preset != "debug" \
            and not args.allow_cpu:
        print(json.dumps({
            "metric": metric, "skipped": "no TPU",
            "error": f"only CPU devices visible "
                     f"(platform={devices[0].platform}, n={len(devices)})",
        }), flush=True)
        return
    ckpt = "BENCH_CHIPSPEED_partial.json"
    partial = {}
    if not args.fresh and os.path.exists(ckpt):
        try:
            with open(ckpt) as f:
                partial = json.load(f)
            done = [k for k, v in partial.items()
                    if isinstance(v, dict) and "aborted" not in v]
            if done:
                print(f"# resuming: arms {done} checkpointed, skipping",
                      flush=True)
        except Exception:
            partial = {}
    for splash, quant, zero in CHIPSPEED_ARMS:
        key = _arm_name(splash, quant, zero)
        cached = partial.get(key)
        if isinstance(cached, dict) and "aborted" not in cached:
            print(f"# {key}: checkpointed, skipping", flush=True)
            continue
        try:
            res = _run_chipspeed_arm(jax, devices, splash, quant, zero, args)
        except Exception as e:  # an OOM/abort must not lose earlier arms
            res = {"aborted": str(e).splitlines()[0][:300]}
        partial[key] = res
        print(f"# {key}: {json.dumps(res)}", flush=True)
        with open(ckpt, "w") as f:
            json.dump(partial, f, indent=1)
    complete = {k: v for k, v in partial.items()
                if isinstance(v, dict) and "aborted" not in v}
    best_key = max(complete, key=lambda k: complete[k].get("mfu", 0.0),
                   default=None)
    out = {
        "metric": metric,
        "value": complete[best_key]["mfu"] if best_key else None,
        "unit": "mfu",
        "best_arm": best_key,
        "vs_off": (round(complete[best_key]["mfu"]
                         / complete["off"]["mfu"], 4)
                   if best_key and complete.get("off", {}).get("mfu")
                   else None),
        "arms": partial,
        "device": getattr(devices[0], "device_kind", "cpu"),
        "n_devices": len(devices),
    }
    with open("BENCH_CHIPSPEED.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="auto",
                   help="auto|debug|llama-1b|gpt2-124m|llama3-8b|mixtral-8x7b")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=0)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--remat", default="save_acts",
                   help="full|save_acts|save_mlp|dots|none — see "
                        "models/transformer.py remat_policy")
    p.add_argument("--backend-timeout", type=float, default=300.0,
                   help="seconds to wait for accelerator backend init "
                        "before emitting a structured {\"skipped\"} line")
    p.add_argument("--allow-cpu", action="store_true",
                   help="run on CPU devices instead of skipping (still "
                        "CPU-sized via --preset; auto on CPU is unwise)")
    p.add_argument("--chipspeed", action="store_true",
                   help="run the >=1B (splash, quant, zero) arm matrix "
                        "with per-arm checkpointing instead of the single "
                        "headline config")
    p.add_argument("--fresh", action="store_true",
                   help="ignore the chipspeed checkpoint and rerun all arms")
    args = p.parse_args()

    try:
        import jax
        import jax.numpy as jnp  # noqa: F401
    except Exception as e:  # a TPU-terminal plugin can raise at import
        print(json.dumps({
            "metric": ("chipspeed_1b_mfu" if args.chipspeed
                       else "train_tokens_per_sec_per_chip"),
            "skipped": "no TPU",
            "error": f"jax import failed: {str(e).splitlines()[0][:300]}",
        }), flush=True)
        return

    if args.chipspeed:
        run_chipspeed(args, jax)
        return

    devices = _devices_or_skip(jax, timeout_s=args.backend_timeout)
    if devices[0].platform == "cpu" and args.preset != "debug" \
            and not args.allow_cpu:
        # TPU absent and the backend fell back to host CPU: an "auto" run
        # would size a multi-B-param model against container RAM and wedge
        # for hours.  Same structured skip as a failed backend init; CPU
        # smoke runs opt in with --preset debug or --allow-cpu.
        print(json.dumps({
            "metric": "train_tokens_per_sec_per_chip",
            "skipped": "no TPU",
            "error": f"only CPU devices visible "
                     f"(platform={devices[0].platform}, n={len(devices)})",
        }), flush=True)
        return
    n = len(devices)
    hbm = 16e9
    try:
        stats = devices[0].memory_stats()
        hbm = stats.get("bytes_limit", hbm)
    except Exception:
        pass
    peak = detect_peak_flops(devices[0])
    is_tpu = devices[0].platform != "cpu"

    cfg, batch, seq = pick_config(args, n, hbm)

    from ray_tpu.parallel import (MeshSpec, init_sharded_state, make_optimizer,
                                  make_train_step)

    mesh = MeshSpec(fsdp=-1).build(devices)
    opt = make_optimizer(total_steps=max(args.steps + args.warmup, 10))
    t0 = time.time()
    state, sh = init_sharded_state(cfg, mesh, opt)
    remat = None if args.remat in ("none", "None") else args.remat
    step = make_train_step(cfg, mesh, opt, sh, remat=remat)
    toks = jax.random.randint(jax.random.PRNGKey(0), (batch, seq + 1), 0,
                              cfg.vocab_size)
    batch_dict = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, batch_dict)
    # Force with a value read: on relay-backed TPU terminals block_until_ready
    # can return before remote execution completes; a host read cannot.
    float(metrics["loss"])
    compile_s = time.time() - t0

    t0 = time.time()
    for _ in range(args.steps):
        state, metrics = step(state, batch_dict)
    final_loss = float(metrics["loss"])
    dt = time.time() - t0

    # Instrumented tail pass: per-step walls with a forcing read each —
    # the runtime-comparable goodput fields (train/observability.py
    # reports the same shapes at runtime).  Kept OUT of the headline
    # timed region: the per-step host read stalls the dispatch pipeline.
    step_walls = []
    for _ in range(min(args.steps, 5)):
        s0 = time.time()
        state, metrics = step(state, batch_dict)
        float(metrics["loss"])
        step_walls.append(time.time() - s0)
    step_walls.sort()
    step_p50 = step_walls[len(step_walls) // 2]
    memory = None
    try:
        ms = devices[0].memory_stats() or {}
        memory = {k: int(ms[k]) for k in
                  ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                  if k in ms} or None
    except Exception:
        pass
    # goodput over this invocation: productive (timed-loop) step time over
    # step time + the compile it paid — compile_s stays split out of every
    # step median above, exactly like the runtime tracker
    goodput = dt / max(compile_s + dt, 1e-9)

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * args.steps / dt
    tok_s_chip = tok_s / n
    flops_per_token = cfg.flops_per_token(seq)
    mfu = (tok_s_chip * flops_per_token) / peak
    vs_baseline = mfu / (0.70 * 0.40)

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
        "mfu": round(mfu, 4),
        "model": f"{cfg.num_params() / 1e6:.0f}M",
        "batch": batch, "seq": seq, "steps": args.steps,
        "n_devices": n,
        "device": getattr(devices[0], "device_kind", "cpu"),
        "peak_bf16_tflops": peak / 1e12,
        "compile_s": round(compile_s, 1),
        "step_ms": round(dt / args.steps * 1000, 1),
        "step_ms_p50": round(step_p50 * 1000, 1),
        "goodput": round(goodput, 4),
        "memory": memory,
        "loss": round(final_loss, 4),
    }))


if __name__ == "__main__":
    main()
