"""Where do tasks_async / actor-call cycles go?  (VERDICT r3 item 9)

Statistical wall-clock profile of the DRIVER process (user thread + the
raytpu-io loop thread) while running the two weakest perf.py scenarios:
``tasks_async`` (1000 noop tasks, one batched get) and
``actor_calls_async_n_n`` (2000 calls over 4 actors).  A sampler thread
walks ``sys._current_frames()`` at ~200 Hz and aggregates inclusive samples
per (function, file) frame, per thread.

Output: PROFILE_CORE.md — top frames per thread per scenario, with the
sample share.  This is the committed analysis artifact; the companion
numbers live in PERF_r04.json.

Usage: python profile_core.py [--hz 200] [--out PROFILE_CORE.md]
"""

from __future__ import annotations

import argparse
import collections
import sys
import threading
import time


class Sampler:
    def __init__(self, hz: float = 200.0):
        self.period = 1.0 / hz
        self.counts: dict = collections.defaultdict(collections.Counter)
        self.totals: collections.Counter = collections.Counter()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._names: dict = {}

    def _run(self):
        me = threading.get_ident()
        while not self._stop.is_set():
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                self.totals[tid] += 1
                f = frame
                seen = set()
                while f is not None:
                    code = f.f_code
                    key = (code.co_name, code.co_filename, f.f_lineno
                           if f is frame else code.co_firstlineno)
                    # inclusive: count each distinct frame once per sample
                    k2 = (code.co_name, code.co_filename)
                    if k2 not in seen:
                        seen.add(k2)
                        self.counts[tid][k2] += 1
                    f = f.f_back
            time.sleep(self.period)

    def start(self):
        for t in threading.enumerate():
            self._names[t.ident] = t.name
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="profiler")
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join()
        for t in threading.enumerate():
            self._names.setdefault(t.ident, t.name)

    def report(self, top: int = 25) -> str:
        out = []
        for tid, ctr in sorted(self.counts.items(),
                               key=lambda kv: -self.totals[kv[0]]):
            total = self.totals[tid]
            if total < 10:
                continue
            name = self._names.get(tid, str(tid))
            out.append(f"\n### thread `{name}` ({total} samples)\n")
            out.append("| share | function | file |")
            out.append("|---|---|---|")
            for (fn, path), n in ctr.most_common(top):
                short = path.split("/ray_tpu/")[-1] if "/ray_tpu/" in path \
                    else path.rsplit("/", 1)[-1]
                out.append(f"| {n / total:.0%} | `{fn}` | {short} |")
        return "\n".join(out)


def scenario_tasks_async(ray_tpu, noop, n=1000):
    ray_tpu.get([noop.remote() for _ in range(n)])


def scenario_actors_nn(ray_tpu, actors, n=2000):
    ray_tpu.get([actors[i % len(actors)].ping.remote() for i in range(n)])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--hz", type=float, default=200.0)
    p.add_argument("--out", default="PROFILE_CORE.md")
    p.add_argument("--rounds", type=int, default=5)
    args = p.parse_args()

    import ray_tpu
    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    def noop(_x=None):
        return None

    @ray_tpu.remote
    class Counter:
        def ping(self):
            return None

    sections = []
    try:
        ray_tpu.get([noop.remote() for _ in range(8)])  # warm pool
        actors = [Counter.remote() for _ in range(4)]
        ray_tpu.get([a.ping.remote() for a in actors])

        for title, fn in [
            ("tasks_async (1000 noop tasks, batched get)",
             lambda: scenario_tasks_async(ray_tpu, noop)),
            ("actor_calls_async_n_n (2000 calls over 4 actors)",
             lambda: scenario_actors_nn(ray_tpu, actors)),
        ]:
            fn()  # warmup round
            s = Sampler(args.hz)
            s.start()
            t0 = time.perf_counter()
            for _ in range(args.rounds):
                fn()
            dt = time.perf_counter() - t0
            s.stop()
            sections.append(f"\n## {title}\n\nwall: {dt:.2f}s for "
                            f"{args.rounds} rounds\n" + s.report())
    finally:
        ray_tpu.shutdown()

    body = ("# Core RPC hot-path profile (driver process)\n\n"
            "Sampled wall-clock stacks (~200 Hz, inclusive per-frame "
            "share per thread) during the two weakest PERF scenarios.\n"
            + "\n".join(sections) + "\n")
    with open(args.out, "w") as f:
        f.write(body)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
