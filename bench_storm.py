"""Load-storm benchmark: SLO-autoscaled serving through a 10x arrival
spike (and, with --chaos, a seeded mid-storm node preemption).

The composition the last four rounds built toward: `serve.slo_signal()`
(the PR-6 autoscaler input) drives the `policy="slo"` autoscaler
(serve/slo_autoscaler.py), the PR-8 graceful-drain path retires replicas
after the storm, and the open-loop harness (serve/loadgen.py) measures
queueing delay honestly — TTFT from SCHEDULED arrival, so a melting
deployment cannot hide behind a slowed client.

Timeline (one burst schedule, three phases):

  warm (base rate) | storm (base * spike) | cooldown (base rate)
                         ^-- optional seeded preempt_node here

Writes ONE JSON (default BENCH_STORM.json): per-phase request rollups
(bench_llm.request_rollup schema), the {arrival rate, TTFT-p95, replica
count} time series, every autoscale decision record, and the acceptance
summary (scale-up latency, TTFT recovery, graceful drain-down, request
error count, SLO-signal gaps).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import time

from bench_llm import request_rollup


def noop_deployment(service_ms: float, autoscaling: dict):
    from ray_tpu import serve

    # max_concurrent_queries bounds per-replica concurrency (the actor's
    # max_concurrency), so one replica's capacity is concurrency /
    # service time — the spike must exceed it or there is no storm
    @serve.deployment(name="storm", max_concurrent_queries=4,
                      health_check_period_s=0.25,
                      health_check_timeout_s=2.0,
                      graceful_shutdown_timeout_s=30.0,
                      autoscaling_config=autoscaling)
    class Storm:
        async def __call__(self, _x=None):
            await asyncio.sleep(service_ms / 1000.0)
            return b"ok"

    return Storm


def llm_tiny_deployment(autoscaling: dict):
    from ray_tpu.serve.llm import llm_deployment
    return llm_deployment(
        "tiny", num_slots=8, max_concurrent_queries=64,
        health_check_period_s=0.5, graceful_shutdown_timeout_s=60.0,
        autoscaling_config=autoscaling,
        # page_size must not exceed the shared-prefix length or the prefix
        # cache never holds a full page and the routing digest stays empty
        engine_kwargs={"paged": True, "page_size": 16})


def split_phase(samples, t0: float, t1: float):
    return [s for s in samples if t0 <= s.t_sched < t1]


def rollup_or_empty(samples, wall_s: float) -> dict:
    ok = [s.rollup_tuple() for s in samples if s.ok]
    out = (request_rollup(ok, wall_s) if ok
           else {"n_requests": 0, "req_per_s": 0.0})
    out["n_errors"] = sum(1 for s in samples if not s.ok)
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["noop", "llm-tiny"], default="noop")
    p.add_argument("--base-rate", type=float, default=6.0,
                   help="steady open-loop arrivals/s")
    p.add_argument("--spike", type=float, default=10.0,
                   help="storm multiplier over the base rate")
    p.add_argument("--warm-s", type=float, default=8.0)
    p.add_argument("--storm-s", type=float, default=15.0)
    p.add_argument("--cool-s", type=float, default=25.0)
    p.add_argument("--service-ms", type=float, default=150.0,
                   help="noop handler service time (one replica's capacity "
                        "= max_concurrent_queries(4) / this)")
    p.add_argument("--ttft-target-ms", type=float, default=400.0,
                   help="the SLO; leave headroom over the service floor "
                        "(p95 at max_concurrent=4 x service-ms is ~2x the "
                        "service time even with zero queueing)")
    p.add_argument("--max-replicas", type=int, default=6)
    p.add_argument("--min-replicas", type=int, default=1,
                   help="floor replicas (the prefix-routing A/B wants >= 2 "
                        "so the router actually has a choice to make)")
    p.add_argument("--chaos", action="store_true",
                   help="seeded preempt_node of the second node mid-storm")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prefix-routing", choices=["on", "off"], default="on",
                   help="cache-aware replica routing (llm-tiny mode): the "
                        "on/off pair is the storm A/B — same seed, same "
                        "shared-prefix traffic, routing as the only delta; "
                        "compare acceptance.prefix_hit_rate")
    p.add_argument("--prefix-pool", type=int, default=8,
                   help="number of distinct shared prefixes in the llm "
                        "traffic (0 disables shared prefixes)")
    p.add_argument("--prefix-len", type=int, default=32,
                   help="shared prefix length in tokens (>= page size so "
                        "the prefix cache can hold full pages)")
    p.add_argument("--out", default="BENCH_STORM.json")
    args = p.parse_args()

    import os

    # before any ray_tpu import: the driver's Config snapshot is what the
    # router reads, and worker nodes inherit the env
    os.environ["RAYTPU_SERVE_PREFIX_ROUTING_ENABLED"] = (
        "1" if args.prefix_routing == "on" else "0")

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.serve import loadgen
    from ray_tpu.util import health

    # shrink the replicas' rolling SLO window so post-storm recovery is
    # visible inside the cooldown phase (node subprocesses inherit this
    # env; the 60s default would pin TTFT-p95 at storm levels long after
    # the storm ends and block the drain-down the benchmark proves)
    os.environ.setdefault("RAYTPU_SERVE_SLO_WINDOW_S", "10")

    rng = random.Random(args.seed)
    total_s = args.warm_s + args.storm_s + args.cool_s
    storm_t0, storm_t1 = args.warm_s, args.warm_s + args.storm_s
    arrivals = loadgen.burst_arrivals(args.base_rate, args.spike,
                                      storm_t0, storm_t1, total_s, rng)

    autoscaling = dict(
        policy="slo", min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        target_ongoing_requests=2.0, ttft_p95_target_ms=args.ttft_target_ms,
        upscale_delay_s=1.0, downscale_delay_s=5.0, min_window_n=8)

    cluster = Cluster(initialize_head=False)
    chaos_rec = None
    try:
        cluster.add_node(num_cpus=4)
        cluster.wait_for_nodes(1)
        cluster.connect_driver()

        if args.mode == "noop":
            dep = noop_deployment(args.service_ms, autoscaling)
            name = "storm"
        else:
            dep = llm_tiny_deployment(autoscaling)
            name = "llm-tiny"
        h = serve.run(dep, timeout_s=300)

        # second node AFTER the control plane landed on node A: the storm
        # scales onto B, and --chaos preempts B (never the controller)
        node_b = cluster.add_node(num_cpus=4)
        cluster.wait_for_nodes(2)

        if args.mode == "noop":
            fire = loadgen.unary_fire(h, lambda _i: None)
        else:
            def payload(idx: int):
                return loadgen.llm_payload(args.seed, idx, prompt_median=48,
                                           prompt_lo=8, prompt_hi=256,
                                           decode_median=16,
                                           prefix_pool=args.prefix_pool,
                                           prefix_len=args.prefix_len)
            fire = loadgen.stream_fire(h, payload, timeout_s=300.0)

        runner = loadgen.StormRunner(fire, max_outstanding=512)
        sampler = loadgen.SignalSampler(name, period_s=0.25, runner=runner)
        sampler.start()

        chaos_at = storm_t0 + args.storm_s / 2
        if args.chaos:
            def arm_chaos():
                spec = {"seed": args.seed, "kills": [
                    {"kind": "preempt_node", "after_s": 0.0, "notice_s": 1.0,
                     "node": node_b.node_id[:8]}]}
                from ray_tpu.core.core_worker import global_worker
                from ray_tpu.core.rpc import run_async
                run_async(global_worker().gcs.call("chaos_set", spec=spec))
                return spec

            import threading

            def chaos_thread():
                time.sleep(chaos_at)
                arm_chaos()

            ct = threading.Thread(target=chaos_thread, daemon=True)
            ct.start()
            chaos_rec = {"kind": "preempt_node", "node": node_b.node_id[:8],
                         "at_s": chaos_at, "notice_s": 1.0,
                         "seed": args.seed}

        print(f"# storm: {len(arrivals)} arrivals over {total_s:.0f}s "
              f"(base {args.base_rate}/s, spike x{args.spike} in "
              f"[{storm_t0:.0f}, {storm_t1:.0f}))", flush=True)
        t_wall = time.time()
        samples = runner.run(arrivals)
        wall = time.time() - t_wall

        # per-replica prefix-cache stats straight off each engine, read
        # BEFORE the drain-down (a retired replica's counters die with
        # it).  Aggregate hit rate is the storm A/B's headline: same
        # seed + traffic, --prefix-routing the only delta.
        prefix_per_replica: dict = {}
        if args.mode == "llm-tiny":
            from ray_tpu.serve.router import get_router
            router = get_router()
            try:
                router._refresh(force=True)
            except Exception:
                pass
            for rep in list(router._table.get(name, [])):
                try:
                    rh = router._replica_handle(rep)
                    st = ray_tpu.get(
                        rh.handle_request.remote((), {}, "stats"),
                        timeout=30)
                    prefix_per_replica[rep] = st.get("prefix_cache") or {}
                except Exception as e:  # noqa: BLE001 — replica mid-drain
                    prefix_per_replica[rep] = {"error": repr(e)}

        # let the autoscaler drain back down before sampling the end state
        deadline = time.monotonic() + args.cool_s + 30
        final_running = None
        while time.monotonic() < deadline:
            sig = serve.slo_signal().get(name) or {}
            final_running = sig.get("running_replicas")
            if final_running == autoscaling["min_replicas"] and \
                    sig.get("queue_depth", 0) == 0:
                break
            time.sleep(0.5)
        series = sampler.stop()
        decisions = serve.autoscale_decisions(limit=100)

        phases = {
            "warm": rollup_or_empty(split_phase(samples, 0, storm_t0),
                                    args.warm_s),
            "storm": rollup_or_empty(split_phase(samples, storm_t0, storm_t1),
                                     args.storm_s),
            "cooldown": rollup_or_empty(split_phase(samples, storm_t1,
                                                    total_s), args.cool_s),
        }

        ups = [d for d in decisions if d["direction"] == "up"]
        downs = [d for d in decisions if d["direction"] == "down"]
        peak_running = max(((s.get("running") or 0) for s in series
                            if "gap" not in s), default=0)
        p95_series = loadgen.windowed_p95_series(samples, window_s=2.0)
        late_storm = [w for w in p95_series
                      if storm_t1 - 4.0 <= w["t"] < storm_t1 + 2.0]
        acceptance = {
            "errors": sum(1 for s in samples if not s.ok),
            "n_requests": len(samples),
            "scale_up_decisions": len(ups),
            "scale_down_decisions": len(downs),
            "peak_running_replicas": peak_running,
            "final_running_replicas": final_running,
            "scaled_down_to_min": final_running ==
            autoscaling["min_replicas"],
            "ttft_p95_late_storm_ms": (min(w["ttft_p95_ms"]
                                           for w in late_storm)
                                       if late_storm else None),
            "ttft_target_ms": args.ttft_target_ms,
            "ttft_recovered_below_target": bool(
                late_storm and min(w["ttft_p95_ms"] for w in late_storm)
                < args.ttft_target_ms),
            "signal_gaps": sampler.gaps(),
            "capped_decisions": [d for d in decisions if d["capped"]],
        }
        if args.mode == "llm-tiny":
            vals = [v for v in prefix_per_replica.values()
                    if isinstance(v, dict) and "lookups" in v]
            lookups = sum(int(v["lookups"]) for v in vals)
            hits = sum(int(v["hits"]) for v in vals)
            acceptance["prefix_routing"] = args.prefix_routing
            acceptance["prefix_hit_rate"] = (
                round(hits / lookups, 4) if lookups else None)
            acceptance["prefix_lookups"] = lookups

        out = {
            "metric": "serve_storm",
            "mode": args.mode,
            "seed": args.seed,
            "wall_s": round(wall, 2),
            "config": {"base_rate": args.base_rate, "spike": args.spike,
                       "warm_s": args.warm_s, "storm_s": args.storm_s,
                       "cool_s": args.cool_s, "service_ms": args.service_ms,
                       "prefix_routing": args.prefix_routing,
                       "prefix_pool": args.prefix_pool,
                       "prefix_len": args.prefix_len,
                       "autoscaling": autoscaling},
            "phases": phases,
            "series": {
                "arrivals": loadgen.arrival_rate_series(arrivals),
                "ttft_p95": p95_series,
                "signal": series,
            },
            "decisions": decisions,
            "chaos": chaos_rec,
            "acceptance": acceptance,
            **({"prefix_per_replica": prefix_per_replica}
               if prefix_per_replica else {}),
            # the storm as the health plane saw it (TTFT_BREACH /
            # SLO_SIGNAL_STALE raises + clears across the phases)
            "health": health.alert_trail(),
        }
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps({"metric": "serve_storm", "phases": phases,
                          "acceptance": {k: v for k, v in acceptance.items()
                                         if k != "signal_gaps"},
                          "signal_gaps": len(acceptance["signal_gaps"])}))
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        cluster.shutdown()


if __name__ == "__main__":
    main()
