"""Broadcast benchmark: one large object fanned out to every node.

BASELINE.md row "1 GiB broadcast to N nodes": the reference uses chunked
parallel push (push_manager.h:30).  Here the equivalent is pull-based tree
propagation: each completed puller registers as a source with the owner
(add_object_location), so later pullers draw from a doubling source set
instead of all hammering the origin.

Run: ``python bench_broadcast.py [--nodes 8] [--mb 100]`` — prints ONE JSON
line with the aggregate fan-out bandwidth and the source-set evidence.

NOTE on single-core CI boxes: all "nodes" share one core, so concurrent
pulls time-slice and ``fanout_speedup_vs_sequential`` cannot exceed ~1.0 —
the number that proves the mechanism there is ``sources_after`` == nodes
(every puller became a source).  On real multi-host hardware the doubling
source set is what turns N pulls into O(log N) rounds.
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=8)
    p.add_argument("--mb", type=int, default=100)
    args = p.parse_args()

    import glob
    import os
    import tempfile

    import numpy as np

    import ray_tpu
    from ray_tpu.core.cluster import Cluster

    # per-chunk/attach timeline (VERDICT r4 weak #4: show WHERE overlap
    # dies) — every agent appends transfer events here
    trace_dir = tempfile.mkdtemp(prefix="bcast-trace-")
    os.environ["RAYTPU_TRANSFER_TRACE_DIR"] = trace_dir

    store_bytes = max(4 * args.mb, 512) * 1024 * 1024
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2,
                                      "object_store_memory": store_bytes})
    node_ids = []
    for _ in range(args.nodes):
        node = cluster.add_node(num_cpus=1, object_store_memory=store_bytes)
        node_ids.append(node.node_id)
    cluster.wait_for_nodes(args.nodes + 1)
    cluster.connect_driver()

    try:
        from ray_tpu.core.common import NodeAffinitySchedulingStrategy

        payload = np.random.default_rng(0).integers(
            0, 255, args.mb * 1024 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(payload)

        @ray_tpu.remote(num_cpus=1)
        def consume(obj):
            return int(obj[:1024].sum())

        expect = int(payload[:1024].sum())

        # Warm the EXACT lease pools the timed phase uses (same function,
        # same per-node affinity) with a tiny payload: the timed section
        # then measures object movement, not worker spawn or lease churn.
        small = ray_tpu.put(np.zeros(2048, np.uint8))
        ray_tpu.get([consume.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(nid, soft=False))).remote(small)
            for nid in node_ids], timeout=300)

        # sequential baseline: one node pulls the object by itself
        t0 = time.monotonic()
        first = consume.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(node_ids[0], soft=False))
        ).remote(ref)
        assert ray_tpu.get(first, timeout=300) == expect
        t_single = time.monotonic() - t0

        # fan-out: every remaining node pulls concurrently (tree sources)
        rest = node_ids[1:]
        t0 = time.monotonic()
        refs = [consume.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(nid, soft=False))).remote(ref)
            for nid in rest]
        results = ray_tpu.get(refs, timeout=600)
        wall = time.monotonic() - t0
        assert all(r == expect for r in results)

        # source-set evidence: the owner should now list most nodes as
        # holders (tree propagation), not just the origin
        w = ray_tpu.core.core_worker.global_worker()
        rec = w.memory_store.get_if_exists(ref.id)
        n_sources = len(rec.locations)

        total_bytes = len(rest) * payload.nbytes
        # fan-out efficiency: serialized pulls would take len(rest)*t_single;
        # >= 1.0 means the concurrent tree matches or beats that
        speedup = (len(rest) * t_single) / wall if wall > 0 else 0.0

        # ---- per-transfer timeline: collect every agent's trace, compute
        # where the time went (chunk pulls vs zero-copy attaches, relay
        # fraction, peak concurrency) and commit the artifact
        events = []
        for path in glob.glob(os.path.join(trace_dir, "transfer-*.jsonl")):
            with open(path) as f:
                events.extend(json.loads(l) for l in f if l.strip())
        events.sort(key=lambda e: e["t0"])
        chunks = [e for e in events if e["kind"] == "chunk"]
        attaches = [e for e in events if e["kind"] == "proxy_attach"]
        origin = cluster.nodes[0].address if cluster.nodes else ""
        relay_bytes = sum(e["bytes"] for e in chunks
                          if e["source"] != origin)
        # peak concurrency: sweep event edges
        edges = [(e["t0"], 1) for e in events] + [(e["t1"], -1)
                                                  for e in events]
        edges.sort()
        cur = peak = 0
        for _, d in edges:
            cur += d
            peak = max(peak, cur)
        summary = {
            "events": len(events),
            "chunk_pulls": len(chunks),
            "zero_copy_attaches": len(attaches),
            "relay_fraction_of_chunk_bytes": round(
                relay_bytes / max(sum(e["bytes"] for e in chunks), 1), 3),
            "peak_concurrent_transfers": peak,
            "mean_attach_ms": round(1000 * float(np.mean(
                [e["t1"] - e["t0"] for e in attaches])), 2) if attaches
            else None,
            "mean_chunk_ms": round(1000 * float(np.mean(
                [e["t1"] - e["t0"] for e in chunks])), 2) if chunks
            else None,
        }
        with open("BENCH_BROADCAST_TIMELINE.json", "w") as f:
            json.dump({"summary": summary, "events": events}, f, indent=1)

        print(json.dumps({
            "metric": "broadcast_fanout_gbps",
            "value": round(total_bytes / wall / 1e9, 3),
            "unit": "GB/s aggregate",
            "vs_baseline": round(speedup / len(rest), 3),
            "fanout_speedup_vs_sequential": round(speedup, 2),
            "single_pull_s": round(t_single, 2),
            "nodes": args.nodes, "mb": args.mb,
            "wall_s": round(wall, 2),
            "sources_after": n_sources,
            "timeline": summary,
        }))
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


if __name__ == "__main__":
    main()
