"""Broadcast benchmark: one large object fanned out to every node.

BASELINE.md row "1 GiB broadcast to N nodes": the reference uses chunked
parallel push (push_manager.h:30).  Here the equivalent is pull-based tree
propagation: each completed puller registers as a source with the owner
(add_object_location), so later pullers draw from a doubling source set
instead of all hammering the origin.

TWO modes run back to back, each with a per-transfer timeline
(RAYTPU_TRANSFER_TRACE_DIR; the artifact VERDICT r4 weak #4 asked for):

* zero-copy — the same-host production path: pullers attach the source's
  /dev/shm arena slice; ZERO bytes move, so "bandwidth" is control-plane
  RPC latency and the evidence is attaches == pullers, ~ms each.
* chunked  — RAYTPU_DISABLE_ZERO_COPY=1 forces the byte path distinct
  HOSTS use: the chunk-ledger stripe (core/transfer.py) pulls each
  object's chunks from EVERY known source concurrently, with partial
  holders relaying ranges they already landed; the evidence is
  relay_fraction > 0.5 (most chunk bytes came off non-origin sources),
  len(sources_used) >= 3, per_source throughput rows, and the ledger
  breakdown (chunks_done / retried / stolen / short) from the
  pull_summary events.

Run: ``python bench_broadcast.py [--nodes 8] [--mb 100]`` — prints ONE
JSON line; full event timelines land in BENCH_BROADCAST_TIMELINE.json.

NOTE on single-core CI boxes: all "nodes" share one core, so concurrent
pulls time-slice and wall-clock speedups are bounded near ~1; the
timeline artifacts are what prove the mechanisms.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import tempfile
import time


def _collect_timeline(trace_dir: str, origin: str) -> tuple:
    import numpy as np

    events = []
    for path in glob.glob(os.path.join(trace_dir, "transfer-*.jsonl")):
        with open(path) as f:
            events.extend(json.loads(l) for l in f if l.strip())
    events.sort(key=lambda e: e["t0"])
    chunks = [e for e in events if e["kind"] == "chunk"]
    attaches = [e for e in events if e["kind"] == "proxy_attach"]
    pulls = [e for e in events if e["kind"] == "pull_summary"]
    transfers = chunks + attaches     # byte-moving spans only
    relay_bytes = sum(e["bytes"] for e in chunks if e["source"] != origin)
    edges = sorted([(e["t0"], 1) for e in transfers]
                   + [(e["t1"], -1) for e in transfers])
    cur = peak = 0
    for _, d in edges:
        cur += d
        peak = max(peak, cur)
    # per-source throughput: bytes each source SERVED over its busy span
    # (the multi-source stripe's evidence — who actually carried the
    # broadcast, at what rate)
    per_source = {}
    socks_of = {}
    for e in chunks:
        row = per_source.setdefault(
            e["source"], {"bytes": 0, "chunks": 0, "stolen": 0,
                          "t0": e["t0"], "t1": e["t1"]})
        row["bytes"] += e["bytes"]
        row["chunks"] += 1
        row["stolen"] += 1 if e.get("stolen") else 0
        row["t0"] = min(row["t0"], e["t0"])
        row["t1"] = max(row["t1"], e["t1"])
        socks_of.setdefault(e["source"], set()).add(e.get("socket", 0))
    for src, row in per_source.items():
        span = max(row.pop("t1") - row.pop("t0"), 1e-9)
        row["gbps"] = round(row["bytes"] / span / 1e9, 3)
        # distinct transfer sockets this source actually served over
        # (the multi-socket plane's evidence; transfer_sockets_per_source)
        row["sockets"] = len(socks_of.get(src, {0}))
    # ledger-state breakdown aggregated over every pull_summary event
    ledger = {"pulls": len(pulls), "chunks_done": 0, "retried": 0,
              "stolen": 0, "short": 0,
              "mean_sources_per_pull": round(float(np.mean(
                  [len(p.get("sources_used", [])) for p in pulls])), 2)
              if pulls else None}
    for p in pulls:
        for k in ("chunks_done", "retried", "stolen", "short"):
            ledger[k] += p.get(k, 0)
    summary = {
        "events": len(events),
        "chunk_pulls": len(chunks),
        "zero_copy_attaches": len(attaches),
        "relay_fraction_of_chunk_bytes": round(
            relay_bytes / max(sum(e["bytes"] for e in chunks), 1), 3),
        "sources_used": sorted({e["source"] for e in transfers}),
        "peak_concurrent_transfers": peak,
        "per_source": per_source,
        "ledger": ledger,
        # the adaptive controller's growth evidence: per-request byte
        # sizes in start order (runs of base chunks grow geometrically
        # under clean completions toward object_transfer_chunk_max)
        "chunk_bytes_trajectory": [e["bytes"] for e in chunks[:256]],
        "sockets_per_source": max(
            (p.get("sockets_per_source", 1) for p in pulls), default=None),
        "mean_attach_ms": round(1000 * float(np.mean(
            [e["t1"] - e["t0"] for e in attaches])), 2) if attaches else None,
        "mean_chunk_ms": round(1000 * float(np.mean(
            [e["t1"] - e["t0"] for e in chunks])), 2) if chunks else None,
    }
    return summary, events


def run_fanout(nodes: int, mb: int, chunked: bool) -> tuple:
    """One full cluster lifecycle measuring the fan-out; returns
    (results_dict, timeline_events)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.core.cluster import Cluster

    trace_dir = tempfile.mkdtemp(prefix="bcast-trace-")
    os.environ["RAYTPU_TRANSFER_TRACE_DIR"] = trace_dir
    if chunked:
        os.environ["RAYTPU_DISABLE_ZERO_COPY"] = "1"
    else:
        os.environ.pop("RAYTPU_DISABLE_ZERO_COPY", None)

    store_bytes = max(4 * mb, 512) * 1024 * 1024
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2,
                                      "object_store_memory": store_bytes})
    node_ids = []
    for _ in range(nodes):
        node = cluster.add_node(num_cpus=1, object_store_memory=store_bytes)
        node_ids.append(node.node_id)
    cluster.wait_for_nodes(nodes + 1)
    cluster.connect_driver()
    try:
        from ray_tpu.core.common import NodeAffinitySchedulingStrategy

        payload = np.random.default_rng(0).integers(
            0, 255, mb * 1024 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(payload)
        # the TRUE byte origin: the agent put() stored into (the driver
        # attaches to the least-loaded agent, not necessarily node 0)
        w0 = ray_tpu.core.core_worker.global_worker()
        origin = w0.memory_store.get_if_exists(ref.id).locations[0][1]

        @ray_tpu.remote(num_cpus=1)
        def consume(obj):
            return int(obj[:1024].sum())

        expect = int(payload[:1024].sum())

        # Warm the EXACT lease pools the timed phase uses (same function,
        # same per-node affinity) with a tiny payload: the timed section
        # then measures object movement, not worker spawn or lease churn.
        small = ray_tpu.put(np.zeros(2048, np.uint8))
        ray_tpu.get([consume.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(nid, soft=False))).remote(small)
            for nid in node_ids], timeout=300)

        # sequential baseline: one node pulls the object by itself
        t0 = time.monotonic()
        first = consume.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(node_ids[0], soft=False))
        ).remote(ref)
        assert ray_tpu.get(first, timeout=300) == expect
        t_single = time.monotonic() - t0

        # fan-out: every remaining node pulls concurrently (tree sources)
        rest = node_ids[1:]
        t0 = time.monotonic()
        refs = [consume.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(nid, soft=False))).remote(ref)
            for nid in rest]
        results = ray_tpu.get(refs, timeout=600)
        wall = time.monotonic() - t0
        assert all(r == expect for r in results)

        # source-set evidence: the owner should now list most nodes as
        # holders (tree propagation), not just the origin
        w = ray_tpu.core.core_worker.global_worker()
        rec = w.memory_store.get_if_exists(ref.id)
        n_sources = len(rec.locations)

        total_bytes = len(rest) * payload.nbytes
        speedup = (len(rest) * t_single) / wall if wall > 0 else 0.0
        summary, events = _collect_timeline(trace_dir, origin)
        return ({
            "gbps_aggregate": round(total_bytes / wall / 1e9, 3),
            "fanout_speedup_vs_sequential": round(speedup, 2),
            "single_pull_s": round(t_single, 2),
            "wall_s": round(wall, 2),
            "sources_after": n_sources,
            "timeline": summary,
        }, events)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        os.environ.pop("RAYTPU_DISABLE_ZERO_COPY", None)
        os.environ.pop("RAYTPU_TRANSFER_TRACE_DIR", None)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=8)
    p.add_argument("--mb", type=int, default=100)
    args = p.parse_args()

    zero_copy, zc_events = run_fanout(args.nodes, args.mb, chunked=False)
    chunked, ch_events = run_fanout(args.nodes, args.mb, chunked=True)
    with open("BENCH_BROADCAST_TIMELINE.json", "w") as f:
        json.dump({"zero_copy": {"summary": zero_copy["timeline"],
                                 "events": zc_events},
                   "chunked": {"summary": chunked["timeline"],
                               "events": ch_events}}, f, indent=1)
    print(json.dumps({
        "metric": "broadcast_fanout_gbps",
        "value": zero_copy["gbps_aggregate"],
        "unit": "GB/s aggregate",
        # the apples-to-apples number vs the reference's chunked
        # push_manager is the BYTE path's fan-out speedup (zero-copy moves
        # no bytes; its wall time is control-plane latency)
        "fanout_speedup_vs_sequential":
            chunked["fanout_speedup_vs_sequential"],
        "vs_baseline": round(
            chunked["fanout_speedup_vs_sequential"] / (args.nodes - 1), 3),
        "nodes": args.nodes, "mb": args.mb,
        "zero_copy": zero_copy,
        "chunked": chunked,
    }))


if __name__ == "__main__":
    main()
