"""Core microbenchmarks — the ``ray_perf.py`` equivalent.

Reference harness: ``python/ray/_private/ray_perf.py``; reference numbers:
BASELINE.md "Core microbenchmarks" (v2.6.3 release log, m4.16xlarge-class,
64 cores).  This box is 1 core, so absolute numbers are not comparable 1:1 —
the table tracks round-over-round movement of the pure-Python substrate and
flags order-of-magnitude regressions vs the reference envelope.

Run: ``python perf.py [--out PERF.json]`` — prints one JSON object with every
metric, and a ``vs_baseline`` per metric where BASELINE.md has a row.
"""

from __future__ import annotations

import argparse
import collections
import json
import time


from ray_tpu.util.procmem import rss_mb as _rss_mb


BASELINE = {
    "tasks_sync": 1329.0,
    "tasks_async": 10940.0,
    "actor_calls_sync_1_1": 2528.0,
    "actor_calls_async_1_1": 8233.0,
    "actor_calls_async_n_n": 32688.0,
    "async_actor_calls_sync_1_1": 1520.0,
    "async_actor_calls_async_1_1": 2683.0,
    "get_small": 6144.0,
    "put_gbps": 18.4,
    "wait_1k_refs": 5.1,
    "pg_create_remove": 983.0,
    "serve_noop_req_s": 630.0,
}


_REPS = 3  # per-metric repetitions inside one suite pass (see --reps)


def timeit(fn, n: int, warmup: int = 1) -> list:
    """Per-rep ops/s samples of fn() called n times (fn may batch internally).

    Repeating the timed region _REPS times per suite pass is what stabilizes
    the headline multipliers: single-shot samples on this 1-core box swing
    +/-40% (e.g. PERF_r05 get_small IQR 52k on a 94k median), and the
    aggregator needs several samples per metric to quote a meaningful
    median + IQR + min."""
    for _ in range(warmup):
        fn()
    samples = []
    for _ in range(max(_REPS, 1)):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        samples.append(n / dt)
    return samples


def run_suite(S: float, with_serve: bool) -> dict:
    """One full pass over the microbench suite on a fresh cluster.
    Every metric maps to a LIST of per-rep ops/s samples."""
    import numpy as np

    import ray_tpu

    # explicit store size: the put benchmark must measure shm write
    # throughput, not LRU spill-to-disk (which the default capacity triggers
    # at 8x64MB)
    ray_tpu.init(num_cpus=8, object_store_memory=2 << 30)
    results = {}

    @ray_tpu.remote
    def noop(_x=None):
        return None

    @ray_tpu.remote
    class Counter:
        def ping(self):
            return None

    @ray_tpu.remote
    class AsyncCounter:
        async def ping(self):
            return None

    try:
        # warm the worker pool
        ray_tpu.get([noop.remote() for _ in range(8)])

        n = int(200 * S)
        results["tasks_sync"] = timeit(
            lambda: [ray_tpu.get(noop.remote()) for _ in range(n)], n)

        n = int(1000 * S)
        results["tasks_async"] = timeit(
            lambda: ray_tpu.get([noop.remote() for _ in range(n)]), n)

        # submit_burst: 1k no-arg tasks submitted back-to-back, then one
        # batched get — end-to-end ops/s PLUS percentiles of the bare
        # .remote() submission call (the user-thread cost the fast path's
        # spec-template cache and submit coalescing shave).
        nb = int(1000 * S)
        results["submit_burst_submit_us_p50"] = []
        results["submit_burst_submit_us_p99"] = []
        burst_calls = [0]

        def burst():
            burst_calls[0] += 1
            t_sub = []
            refs = []
            for _ in range(nb):
                s0 = time.perf_counter()
                refs.append(noop.remote())
                t_sub.append(time.perf_counter() - s0)
            ray_tpu.get(refs)
            if burst_calls[0] == 1:
                return  # timeit()'s warmup pass: cold-path latencies
                # (lease acquisition, spec-cache fill) must not skew the
                # warm percentiles — ops/s already excludes warmup
            t_sub.sort()
            results["submit_burst_submit_us_p50"].append(
                t_sub[len(t_sub) // 2] * 1e6)
            results["submit_burst_submit_us_p99"].append(
                t_sub[min(len(t_sub) - 1, int(len(t_sub) * 0.99))] * 1e6)

        results["submit_burst"] = timeit(burst, nb)

        # submit_churn: sustained WINDOW-deep submit/drain steady state —
        # every completion admits the next submission, so this measures
        # the pipeline the admission gate enforces at production depths
        # (ops/s, bare-submit latency percentiles, and the RSS the steady
        # state retains), not a one-shot burst.
        nc = int(4000 * S)
        window = 1000
        results["submit_churn_submit_us_p50"] = []
        results["submit_churn_submit_us_p99"] = []
        results["submit_churn_rss_delta_mb"] = []
        churn_calls = [0]

        def churn():
            churn_calls[0] += 1
            rss0 = _rss_mb()
            t_sub = []
            dq = collections.deque()
            for _ in range(nc):
                s0 = time.perf_counter()
                dq.append(noop.remote())
                t_sub.append(time.perf_counter() - s0)
                if len(dq) >= window:
                    ray_tpu.get(dq.popleft())
            ray_tpu.get(list(dq))
            if churn_calls[0] == 1:
                return  # warmup pass: exclude cold-path latencies
            t_sub.sort()
            results["submit_churn_submit_us_p50"].append(
                t_sub[len(t_sub) // 2] * 1e6)
            results["submit_churn_submit_us_p99"].append(
                t_sub[min(len(t_sub) - 1, int(len(t_sub) * 0.99))] * 1e6)
            results["submit_churn_rss_delta_mb"].append(
                max(0.0, _rss_mb() - rss0))

        results["submit_churn"] = timeit(churn, nc)

        a = Counter.remote()
        ray_tpu.get(a.ping.remote())
        n = int(300 * S)
        results["actor_calls_sync_1_1"] = timeit(
            lambda: [ray_tpu.get(a.ping.remote()) for _ in range(n)], n)

        n = int(2000 * S)
        results["actor_calls_async_1_1"] = timeit(
            lambda: ray_tpu.get([a.ping.remote() for _ in range(n)]), n)

        actors = [Counter.remote() for _ in range(4)]
        ray_tpu.get([x.ping.remote() for x in actors])
        n = int(2000 * S)
        results["actor_calls_async_n_n"] = timeit(
            lambda: ray_tpu.get([actors[i % 4].ping.remote()
                                 for i in range(n)]), n)

        aa = AsyncCounter.remote()
        ray_tpu.get(aa.ping.remote())
        n = int(300 * S)
        results["async_actor_calls_sync_1_1"] = timeit(
            lambda: [ray_tpu.get(aa.ping.remote()) for _ in range(n)], n)
        n = int(2000 * S)
        results["async_actor_calls_async_1_1"] = timeit(
            lambda: ray_tpu.get([aa.ping.remote() for _ in range(n)]), n)

        small = ray_tpu.put(np.zeros(16))
        n = int(2000 * S)
        results["get_small"] = timeit(
            lambda: [ray_tpu.get(small) for _ in range(n)], n)

        big = np.zeros(64 * 1024 * 1024, np.uint8)  # 64 MB
        n = max(int(8 * S), 2)

        def put_big():
            for _ in range(n):
                ray_tpu.put(big)

        results["put_gbps"] = [ops * big.nbytes / 1e9
                               for ops in timeit(put_big, n)]

        refs = [noop.remote() for _ in range(1000)]
        ray_tpu.get(refs)
        n = max(int(20 * S), 5)
        results["wait_1k_refs"] = timeit(
            lambda: [ray_tpu.wait(refs, num_returns=1000, timeout=10)
                     for _ in range(n)], n)

        n = max(int(20 * S), 5)

        def pg_cycle():
            for _ in range(n):
                pg = ray_tpu.placement_group([{"CPU": 1}])
                pg.ready(timeout=30)
                ray_tpu.remove_placement_group(pg)

        results["pg_create_remove"] = timeit(pg_cycle, n)

        if with_serve:
            # free the microbench actors' CPUs for the serve replicas
            for actor in [a, aa, *actors]:
                ray_tpu.kill(actor)
            from ray_tpu import serve

            @serve.deployment(num_replicas=2)
            def snoop(_x=None):
                return b"ok"

            h = serve.run(snoop)
            for _ in range(20):
                h.remote().result()
            n = int(300 * S)
            results["serve_noop_req_s"] = timeit(
                lambda: [h.remote().result() for _ in range(n)], n)
            serve.shutdown()
    finally:
        ray_tpu.shutdown()
    return results


#: the "off" arm of the fast-path A/B: result inlining, spec template
#: caching, and lease pipelining all disabled — results route through the
#: shm store (worker-side store_create + caller-side fetch per result) and
#: every submission re-encodes its full spec, isolating exactly what the
#: submission fast path buys on this box in this run.
FASTPATH_OFF = {"inline_result_max_bytes": 0,
                "spec_cache_enabled": False,
                "lease_pipeline_window": 0}


def _measure_submission(S: float, system_config: dict | None) -> dict:
    """One fresh-cluster measurement of the submission-plane metrics only
    (the A/B arms; full-suite metrics stay with run_suite)."""
    import ray_tpu
    ray_tpu.init(num_cpus=8, object_store_memory=2 << 30,
                 _system_config=system_config or None)
    out = {}

    @ray_tpu.remote
    def noop(_x=None):
        return None

    @ray_tpu.remote
    class Counter:
        def ping(self):
            return None

    try:
        ray_tpu.get([noop.remote() for _ in range(8)])
        n = int(1000 * S)
        out["tasks_async"] = max(timeit(
            lambda: ray_tpu.get([noop.remote() for _ in range(n)]), n))
        a = Counter.remote()
        ray_tpu.get(a.ping.remote())
        n = int(300 * S)
        out["actor_calls_sync_1_1"] = max(timeit(
            lambda: [ray_tpu.get(a.ping.remote()) for _ in range(n)], n))
    finally:
        ray_tpu.shutdown()
    return out


def _measure_serve_reqs(S: float, system_config: dict | None) -> dict:
    """One fresh-cluster serve request-throughput measurement (the
    serve-observability A/B arms): a 2-replica noop deployment driven via
    the handle path, sequential (latency-bound) and pipelined."""
    import ray_tpu
    from ray_tpu import serve
    ray_tpu.init(num_cpus=8, _system_config=system_config or None)
    out = {}
    try:
        @serve.deployment(num_replicas=2, max_concurrent_queries=64)
        def snoop(_x=None):
            return b"ok"

        h = serve.run(snoop)
        for _ in range(20):
            h.remote().result()
        n = int(300 * S)
        out["serve_noop_req_s"] = max(timeit(
            lambda: [h.remote().result() for _ in range(n)], n))
        n = int(600 * S)

        def pipelined():
            rs = [h.remote() for _ in range(n)]
            for r in rs:
                r.result()

        out["serve_pipelined_req_s"] = max(timeit(pipelined, n))
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
    return out


def run_ab_serve_metrics(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: serve_metrics_enabled on vs off — the
    serve observability plane's request-throughput overhead (the ISSUE-6
    acceptance gate: <= 5%)."""
    on_runs, off_runs = [], []
    off_cfg = {"serve_metrics_enabled": False}
    for i in range(pairs):
        on_runs.append(_measure_serve_reqs(S, None))
        off_runs.append(_measure_serve_reqs(S, dict(off_cfg)))
        print(f"# serve ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": off_cfg, "ratio_on_off": ratio}


def _measure_specroute(S: float, on: bool) -> dict:
    """One fresh-cluster LLM serving measurement for the speculative +
    cache-routed A/B (PR-19 gate): 2 replicas of a compute-bound CPU toy
    model behind the real handle -> router -> replica -> engine path.

    ON arm: speculative decoding (1-layer draft, verify-window target
    step) + prefix-cache-aware routing.  OFF arm: dense decode + pure
    power-of-two-choices.  Both arms serve the SAME damped checkpoint and
    the SAME seeded shared-prefix traffic — the decode/routing planes are
    the only delta.  The model is deliberately deeper/wider than the
    'tiny' preset: speculation pays when layer compute dominates the
    per-step fixed cost (embed + lm_head + dispatch), which is also the
    regime real targets live in; on a toy-tiny config the fixed cost
    swamps the drafted layers and speculation measures slower."""
    import os
    import queue
    import threading
    import time as _time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import loadgen

    sys_cfg = None if on else {"serve_prefix_routing_enabled": False}
    ray_tpu.init(num_cpus=8, _system_config=sys_cfg)
    out = {"arm": "spec+routed" if on else "dense+p2c"}
    try:
        @serve.deployment(name="specbench", num_replicas=2,
                          max_concurrent_queries=64,
                          health_check_timeout_s=600.0)
        class SpecBench:
            """LLM replica over a damped checkpoint (speculative.py's
            honest-about-itself benchmark trick: tail layers' output
            projections scaled so target ~= draft + small residual while
            the target still pays full depth)."""

            def __init__(self, spec: bool):
                import jax
                import jax.numpy as jnp
                from ray_tpu.models import speculative as specmod
                from ray_tpu.models import transformer
                from ray_tpu.models.config import TransformerConfig
                from ray_tpu.serve.llm import LLMEngine
                cfg = TransformerConfig(
                    vocab_size=512, num_layers=8, hidden_size=256,
                    num_heads=8, num_kv_heads=4, mlp_size=1024,
                    max_seq_len=512)
                params = transformer.init_params(
                    jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
                params = specmod.damp_block_outputs(params, 0.02,
                                                    from_layer=1)
                kw = dict(paged=True, page_size=16, buckets=(64, 128),
                          warmup_buckets=True, steps_per_dispatch=12)
                if spec:
                    kw.update(spec_decode_enabled=True, spec_k=6,
                              spec_draft_layers=1)
                self.engine = LLMEngine(cfg, params, num_slots=16,
                                        max_len=512, **kw)

            async def __call__(self, request):
                import asyncio
                from ray_tpu.serve.llm import _FLUSH  # noqa: F401
                body = (request.json() if hasattr(request, "json")
                        else request)
                req = self.engine.submit(
                    body["tokens"],
                    max_tokens=int(body.get("max_tokens", 32)))
                loop = asyncio.get_event_loop()
                while True:
                    item = await loop.run_in_executor(None, req.out.get)
                    if not isinstance(item, int):
                        if isinstance(item, BaseException):
                            raise item
                        return
                    yield item

            def stats(self) -> dict:
                return self.engine.breakdown()

            def prefix_digest(self):
                from ray_tpu.core.config import get_config
                cap = int(getattr(get_config(),
                                  "serve_prefix_digest_max", 32))
                return self.engine.prefix_digest(cap)

        h = serve.run(SpecBench.bind(spec=on), timeout_s=600)
        n = max(12, int(24 * S))
        payloads = [loadgen.llm_payload(
            1234, i, prompt_median=64, prompt_lo=48, prompt_hi=96,
            decode_median=24, decode_lo=16, decode_hi=32, vocab=500,
            prefix_pool=6, prefix_len=64) for i in range(n)]
        # warm both replicas' decode/spec programs before timing
        for _ in range(4):
            sum(1 for _ in h.stream({"tokens": payloads[0]["tokens"][:],
                                     "max_tokens": 4}))
        work: queue.Queue = queue.Queue()
        for pl in payloads:
            work.put(pl)
        counts = []
        lock = threading.Lock()

        def client():
            while True:
                try:
                    pl = work.get_nowait()
                except queue.Empty:
                    return
                ntok = sum(1 for _ in h.stream(dict(pl), timeout_s=600.0))
                with lock:
                    counts.append(ntok)

        t0 = _time.monotonic()
        threads = [threading.Thread(target=client) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = _time.monotonic() - t0
        out["tok_s"] = round(sum(counts) / wall, 2)
        out["n_requests"] = len(counts)
        out["wall_s"] = round(wall, 2)
        # per-replica engine stats: spec acceptance + prefix hit rate
        from ray_tpu.serve.router import get_router
        router = get_router()
        router._refresh(force=True)
        spec_tot = {"tokens": 0, "drafted": 0, "accepted": 0, "rounds": 0}
        lookups = hits = 0
        for rep in list(router._table.get("specbench", [])):
            try:
                st = ray_tpu.get(router._replica_handle(rep)
                                 .handle_request.remote((), {}, "stats"),
                                 timeout=60)
            except Exception:  # noqa: BLE001 — stats are additive
                continue
            sp = st.get("spec")
            if sp:
                for k in spec_tot:
                    spec_tot[k] += int(sp.get(k, 0))
            pc = st.get("prefix_cache") or {}
            lookups += int(pc.get("lookups", 0))
            hits += int(pc.get("hits", 0))
        if spec_tot["drafted"]:
            out["spec_acceptance"] = round(
                spec_tot["accepted"] / spec_tot["drafted"], 4)
            out["spec_tokens_per_round"] = round(
                spec_tot["tokens"] / max(spec_tot["rounds"], 1), 2)
        out["prefix_hit_rate"] = (round(hits / lookups, 4)
                                  if lookups else None)
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
    return out


def run_ab_specroute(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: speculative decode + prefix-cache-aware
    routing ON vs dense decode + load-only p2c (the PR-19 acceptance
    gate: spec+routed decode tokens/s >= 1.3x the dense arm on the same
    damped CPU model + seeded shared-prefix traffic)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_specroute(S, True))
        off_runs.append(_measure_specroute(S, False))
        print(f"# specroute ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = round(med([r["tok_s"] for r in on_runs])
                  / max(med([r["tok_s"] for r in off_runs]), 1e-9), 3)
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "ratio_on_off": {"tok_s": ratio},
            "gate": {"min_ratio": 1.3, "passed": ratio >= 1.3}}


def _measure_autoscale_reqs(S: float, slo_policy: bool) -> dict:
    """One fresh-cluster serve request-throughput measurement for the
    autoscaler A/B: a steady 2-replica noop deployment — the ON arm runs
    the policy="slo" control loop (targets high enough that steady load
    never trips a scale event: the measured cost is the per-reconcile
    signal rollup + policy tick, not replica churn), the OFF arm pins
    num_replicas=2 with no autoscaling at all."""
    import ray_tpu
    from ray_tpu import serve
    ray_tpu.init(num_cpus=8)
    out = {}
    try:
        opts = dict(max_concurrent_queries=64)
        if slo_policy:
            opts["autoscaling_config"] = dict(
                policy="slo", min_replicas=2, max_replicas=4,
                target_ongoing_requests=1000.0, ttft_p95_target_ms=60_000.0,
                upscale_delay_s=3.0, downscale_delay_s=30.0)
        else:
            opts["num_replicas"] = 2

        @serve.deployment(**opts)
        def anoop(_x=None):
            return b"ok"

        h = serve.run(anoop)
        for _ in range(20):
            h.remote().result()
        n = int(300 * S)
        out["serve_noop_req_s"] = max(timeit(
            lambda: [h.remote().result() for _ in range(n)], n))
        n = int(600 * S)

        def pipelined():
            rs = [h.remote() for _ in range(n)]
            for r in rs:
                r.result()

        out["serve_pipelined_req_s"] = max(timeit(pipelined, n))
        # the A/B is only valid if the policy held steady: a scale event
        # mid-measurement would be measuring replica churn, not overhead
        if slo_policy:
            reps = serve.status()["anoop"]["replicas"]
            out["replicas_end"] = len(
                [r for r in reps if r["state"] == "RUNNING"])
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
    return out


def run_ab_autoscale(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: SLO autoscaler policy on vs no
    autoscaling, over a steady noop deployment (the ISSUE-15 acceptance
    gate: <= 5% request-throughput overhead for the control loop)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_autoscale_reqs(S, True))
        off_runs.append(_measure_autoscale_reqs(S, False))
        print(f"# autoscale ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    keys = [k for k in on_runs[0] if k in off_runs[0]]
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in keys}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": "num_replicas=2, autoscaling=None",
            "ratio_on_off": ratio}


#: the "off" arm of the train-observability A/B: the kill switch sheds the
#: step/stage histograms, MFU/goodput gauges, memory sampling AND the
#: per-step trace spans — isolating exactly what train_metrics_enabled
#: costs a tight report-every-step CPU loop.
TRAIN_OBS_OFF = {"train_metrics_enabled": False}


def _measure_train_obs(S: float, system_config: dict | None) -> dict:
    """One fresh-cluster measurement of a small CPU train loop's
    steps/s (the train-observability A/B arms): a 1-worker
    DataParallelTrainer whose loop stamps the data_wait/step_compute
    phases and reports EVERY step — the densest instrumentation pattern
    a real loop would use."""
    import tempfile

    import ray_tpu
    ray_tpu.init(num_cpus=4, _system_config=system_config or None)
    out = {}
    try:
        from ray_tpu.train import (DataParallelTrainer, RunConfig,
                                   ScalingConfig)
        steps = max(int(200 * S), 20)

        def loop(config):
            import time as _t

            from ray_tpu import train
            obs = train.get_context().observability()
            obs.set_model(flops_per_token=1e3, tokens_per_step=1024,
                          peak_flops=1e12)
            n = config["steps"]
            t0 = _t.perf_counter()
            for i in range(n):
                with obs.phase("data_wait"):
                    pass
                with obs.phase("step_compute"):
                    pass
                train.report(
                    {"step": i,
                     "steps_per_s": n / max(_t.perf_counter() - t0, 1e-9)})

        trainer = DataParallelTrainer(
            train_loop_per_worker=loop,
            train_loop_config={"steps": steps},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="ab-train-obs",
                                 storage_path=tempfile.mkdtemp()))
        result = trainer.fit()
        out["train_steps_per_s"] = result.metrics["steps_per_s"]
    finally:
        ray_tpu.shutdown()
    return out


def run_ab_train_obs(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: train_metrics_enabled on vs off — the
    train observability plane's per-step overhead (the ISSUE-10
    acceptance gate: <= 5%)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_train_obs(S, None))
        off_runs.append(_measure_train_obs(S, dict(TRAIN_OBS_OFF)))
        print(f"# train-obs ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": TRAIN_OBS_OFF, "ratio_on_off": ratio}


def _measure_elastic(S: float, mode: str) -> dict:
    """One fresh-cluster run of a fixed training workload (epochs x 100 ms
    of "compute", checkpoint every epoch) under a seeded mid-run
    preemption, for the elastic-vs-restart A/B arms:

    - ``elastic``:  ScalingConfig(min_workers=1) — the drain notice
      resizes the group 2 -> 1 in place, then back up when the
      replacement node lands;
    - ``restart``:  rigid world size + FailureConfig retries — the same
      preemption kills the run, which restarts from the latest
      checkpoint once the replacement node can host the full group;
    - ``baseline``: same cluster and workload, no chaos (the undisturbed
      goodput yardstick).

    The chaos schedule (seed, after_s, notice_s) and the 2 s
    replacement-node lag are identical for elastic and restart, so the
    measured gap is exactly the recovery-path cost."""
    import tempfile
    import threading

    import ray_tpu
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.rpc import run_async

    epochs = max(int(240 * S), 30)
    sleep_s = 0.1
    cluster = Cluster(initialize_head=False)
    out = {}
    try:
        n1 = cluster.add_node(num_cpus=4)
        n2 = cluster.add_node(num_cpus=4)
        cluster.wait_for_nodes(2)
        info = cluster.connect_driver()
        from ray_tpu.core.core_worker import global_worker
        from ray_tpu.train import (Checkpoint, DataParallelTrainer,
                                   FailureConfig, RunConfig, ScalingConfig)
        # info["node_id"] is None when joining an existing cluster: identify
        # the driver by its attached agent's address instead
        victim = n2 if n1.address == global_worker().agent_address else n1
        if mode != "baseline":
            spec = {"seed": 23, "kills": [
                {"kind": "preempt_node", "after_s": 3.0, "notice_s": 2.0,
                 "node": victim.node_id[:8]}]}
            run_async(global_worker().gcs.call("chaos_set", spec=spec))

            def _replace():  # the spot market delivers a replacement node
                deadline = time.monotonic() + 120
                while (victim.proc.poll() is None
                       and time.monotonic() < deadline):
                    time.sleep(0.1)
                time.sleep(2.0)  # provisioning lag, identical for both arms
                cluster.add_node(num_cpus=4)

            threading.Thread(target=_replace, daemon=True).start()

        def loop(config):
            import json as _json
            import os as _os
            import tempfile as _tmp
            import time as _t

            from ray_tpu import train
            from ray_tpu.train import Checkpoint as _Ckpt
            rank0 = train.get_context().get_world_rank() == 0
            start = 0
            ckpt = train.get_checkpoint()
            if ckpt:
                with open(_os.path.join(ckpt.path, "e.json")) as f:
                    start = _json.load(f)["epoch"] + 1
            for e in range(start, config["epochs"]):
                _t.sleep(config["sleep_s"])
                ck = None
                if rank0:
                    d = _tmp.mkdtemp()
                    with open(_os.path.join(d, "e.json"), "w") as f:
                        _json.dump({"epoch": e}, f)
                    ck = _Ckpt(d)
                train.report({"epoch": e}, checkpoint=ck)

        scaling = ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 3.0},
            min_workers=1 if mode == "elastic" else None)
        failures = FailureConfig(max_failures=5 if mode == "restart" else 0)
        trainer = DataParallelTrainer(
            train_loop_per_worker=loop,
            train_loop_config={"epochs": epochs, "sleep_s": sleep_s},
            scaling_config=scaling,
            run_config=RunConfig(name=f"ab-elastic-{mode}",
                                 storage_path=tempfile.mkdtemp(),
                                 failure_config=failures))
        t0 = time.perf_counter()
        result = trainer.fit()
        wall = time.perf_counter() - t0
        assert result.error is None, f"{mode} arm failed: {result.error!r}"
        assert result.metrics["epoch"] == epochs - 1
        out["wall_s"] = round(wall, 3)
        # the workload's intrinsic productive time over actual wall clock:
        # one comparable goodput number for all three arms
        out["goodput"] = round(epochs * sleep_s / wall, 4)
        out["resizes"] = result.num_resizes
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()
    return out


def run_ab_elastic(S: float, pairs: int) -> dict:
    """Elastic resize vs restart-from-checkpoint on the SAME seeded chaos
    schedule, plus an undisturbed baseline (the ISSUE-18 acceptance
    gates: elastic goodput >= 80% of undisturbed; resize strictly
    cheaper than restart)."""
    arms = {"elastic": [], "restart": [], "baseline": []}
    for i in range(pairs):
        for mode in ("elastic", "restart", "baseline"):
            arms[mode].append(_measure_elastic(S, mode))
        print(f"# elastic ab pair {i + 1}/{pairs}: "
              f"elastic={arms['elastic'][-1]} "
              f"restart={arms['restart'][-1]} "
              f"baseline={arms['baseline'][-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    g = {m: med([r["goodput"] for r in arms[m]]) for m in arms}
    w = {m: med([r["wall_s"] for r in arms[m]]) for m in arms}
    return {"pairs": arms,
            "goodput": {m: round(v, 4) for m, v in g.items()},
            "wall_s": {m: round(v, 3) for m, v in w.items()},
            "elastic_vs_baseline_goodput": round(
                g["elastic"] / max(g["baseline"], 1e-9), 3),
            "elastic_vs_restart_wall": round(
                w["elastic"] / max(w["restart"], 1e-9), 3)}


#: the "off" arm of the scheduler-observability A/B: the kill switch sheds
#: loop busy-fraction sampling, per-GCS-handler busy attribution, the
#: owner serialize/flush histograms and the backpressure counters —
#: isolating what sched_metrics_enabled costs the submission hot path.
SCHED_OBS_OFF = {"sched_metrics_enabled": False}


def _measure_sched_obs(S: float, system_config: dict | None) -> dict:
    """One fresh-cluster measurement of the sched-observability A/B arms:
    tasks_async (the owner-loop-bound path the saturation metrics watch)
    plus submit_burst ops/s and bare-submit p99."""
    import ray_tpu
    ray_tpu.init(num_cpus=8, object_store_memory=2 << 30,
                 _system_config=system_config or None)
    out = {}

    @ray_tpu.remote
    def noop(_x=None):
        return None

    try:
        ray_tpu.get([noop.remote() for _ in range(8)])
        n = int(1000 * S)
        out["tasks_async"] = max(timeit(
            lambda: ray_tpu.get([noop.remote() for _ in range(n)]), n))
        nb = int(1000 * S)
        sub_p99 = []
        calls = [0]

        def burst():
            calls[0] += 1
            t_sub = []
            refs = []
            for _ in range(nb):
                s0 = time.perf_counter()
                refs.append(noop.remote())
                t_sub.append(time.perf_counter() - s0)
            ray_tpu.get(refs)
            if calls[0] == 1:
                return  # warmup pass
            t_sub.sort()
            sub_p99.append(
                t_sub[min(len(t_sub) - 1, int(len(t_sub) * 0.99))] * 1e6)

        out["submit_burst"] = max(timeit(burst, nb))
        out["submit_burst_submit_us_p99"] = (
            sorted(sub_p99)[len(sub_p99) // 2] if sub_p99 else None)
    finally:
        ray_tpu.shutdown()
    return out


def run_ab_sched_obs(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: sched_metrics_enabled on vs off over
    tasks_async + submit_burst (the ISSUE-11 acceptance gate: <= 5%
    overhead)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_sched_obs(S, None))
        off_runs.append(_measure_sched_obs(S, dict(SCHED_OBS_OFF)))
        print(f"# sched ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in ("tasks_async", "submit_burst")}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": SCHED_OBS_OFF, "ratio_on_off": ratio}


#: both arms of the health-plane A/B run the detectors' tick cadences
#: HOT (2 Hz health check + scrape, dashboard head up) so the on-arm pays
#: every cost the plane can impose; the off-arm differs by ONE switch.
HEALTH_AB_BASE = {"health_check_period_s": 0.5,
                  "metrics_scrape_period_s": 0.5}
HEALTH_OFF = {"health_metrics_enabled": False}


def _measure_health(S: float, system_config: dict | None) -> dict:
    """One fresh-cluster measurement of the health-plane A/B arms:
    submit_churn (window-deep submit/drain — the owner/GCS loops the
    GCS-side rules watch) + serve_noop req/s (the loop the head-side
    SLO rules watch), with the dashboard head running so the scrape-loop
    detector is actually on the clock."""
    import collections
    import ray_tpu
    from ray_tpu import serve
    cfg = dict(HEALTH_AB_BASE)
    cfg.update(system_config or {})
    ray_tpu.init(num_cpus=8, _system_config=cfg)
    out = {}
    try:
        from ray_tpu.dashboard import head as dash_head
        dash_head.start_dashboard()

        @ray_tpu.remote
        def noop(_x=None):
            return None

        ray_tpu.get([noop.remote() for _ in range(8)])
        nc = int(2000 * S)
        window = 500

        def churn():
            dq = collections.deque()
            for _ in range(nc):
                dq.append(noop.remote())
                if len(dq) >= window:
                    ray_tpu.get(dq.popleft())
            ray_tpu.get(list(dq))

        out["submit_churn"] = max(timeit(churn, nc))

        @serve.deployment(num_replicas=2, max_concurrent_queries=64)
        def snoop(_x=None):
            return b"ok"

        h = serve.run(snoop)
        for _ in range(20):
            h.remote().result()
        n = int(300 * S)
        out["serve_noop_req_s"] = max(timeit(
            lambda: [h.remote().result() for _ in range(n)], n))
        serve.shutdown()
        dash_head.stop_dashboard()
    finally:
        ray_tpu.shutdown()
    return out


def run_ab_health(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: health_metrics_enabled on vs off over
    submit_churn + serve_noop with hot detector cadences (the ISSUE-17
    acceptance gate: <= 5% overhead; off restores zero series)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_health(S, None))
        off_runs.append(_measure_health(S, dict(HEALTH_OFF)))
        print(f"# health ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": HEALTH_OFF, "base_config": HEALTH_AB_BASE,
            "ratio_on_off": ratio}


#: the "off" arm of the object-observability A/B: the object plane's one
#: kill switch — no raytpu_object_*/raytpu_mem_* series, no flight-recorder
#: events, no copy-ledger accounting, no transfer-ring writes.
OBJECT_OBS_OFF = {"object_metrics_enabled": False}


def _measure_object_obs(S: float, system_config: dict | None) -> dict:
    """One fresh-cluster measurement of the object-plane A/B arms: put
    GB/s (the instrumented 1-copy path), same-host large get ops/s (the
    instrumented 0-copy path), and an 8-way large-arg fan-out (every
    worker fetches the same plasma object — the broadcast-shaped path)."""
    import numpy as np

    import ray_tpu
    ray_tpu.init(num_cpus=8, object_store_memory=2 << 30,
                 _system_config=system_config or None)
    out = {}
    try:
        big = np.zeros(64 * 1024 * 1024, np.uint8)  # 64 MB
        n = max(int(8 * S), 2)

        def put_big():
            for _ in range(n):
                ray_tpu.put(big)

        out["put_gbps"] = max(ops * big.nbytes / 1e9
                              for ops in timeit(put_big, n))

        ref = ray_tpu.put(big)
        ng = max(int(40 * S), 5)
        out["get_big"] = max(timeit(
            lambda: [ray_tpu.get(ref) for _ in range(ng)], ng))

        @ray_tpu.remote
        def touch(obj):
            return int(obj[0])

        ray_tpu.get([touch.remote(ref) for _ in range(8)])  # warmup
        nb = max(int(6 * S), 2)

        def fanout():
            for _ in range(nb):
                ray_tpu.get([touch.remote(ref) for _ in range(8)])

        out["arg_fanout_8"] = max(ops * 8 for ops in timeit(fanout, nb))
    finally:
        ray_tpu.shutdown()
    return out


def run_ab_object_obs(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: object_metrics_enabled on vs off over
    put/get/fan-out (the ISSUE-12 acceptance gate: <= 5% overhead)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_object_obs(S, None))
        off_runs.append(_measure_object_obs(S, dict(OBJECT_OBS_OFF)))
        print(f"# object ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": OBJECT_OBS_OFF, "ratio_on_off": ratio}


#: the "off" arm of the zero-copy-put + wire-rate-transfer A/B: the exact
#: pre-PR data plane — classic serialize-then-copy put (one write_into
#: memcpy), one socket per (puller, source) pair, fixed chunk size (no
#: adaptive growth).
ZCPUT_OFF = {"zero_copy_put_enabled": False,
             "transfer_sockets_per_source": 1,
             "object_transfer_chunk_bytes": 8 * 1024 * 1024,
             "object_transfer_chunk_max": 0}


def run_ab_zcput(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: zero-copy put + multi-socket adaptive
    transfer ON vs the prior 1-copy/fixed-chunk plane (the ISSUE-14
    gates: put_gbps >= 1.5x with the ledger showing put/copies=0, and the
    off arm's put_gbps/get_big within the <=5% regression envelope of
    PERF_r13)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_object_obs(S, None))
        off_runs.append(_measure_object_obs(S, dict(ZCPUT_OFF)))
        print(f"# zcput ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": ZCPUT_OFF, "ratio_on_off": ratio}


#: the "off" arm of the batched-submission A/B: one task per push RPC, one
#: lease per request RPC, one actor call per batch — the unbatched
#: submission plane the scale-envelope work replaced.
SUBMIT_BATCH_OFF = {"submit_batching_enabled": False}


def run_ab_submit_batching(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: batched submission on vs off (the ISSUE-7
    acceptance gate: >= 1.5x tasks_async)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_submission(S, None))
        off_runs.append(_measure_submission(S, dict(SUBMIT_BATCH_OFF)))
        print(f"# submit ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": SUBMIT_BATCH_OFF, "ratio_on_off": ratio}


#: the "off" arm of the horizontal-control-plane A/B (PR-13): the PRE-PR
#: submission/completion plane — per-result push frames, per-ref get
#: waits, 16-task push batches, one GCS process (gcs_table_shards=1), one
#: connection, no shard processes, no serialization pool, no lanes.
CPSHARD_OFF = {
    "completion_batching_enabled": False,
    "max_tasks_in_flight_per_worker": 16,
    "gcs_table_shards": 1,
    "gcs_shard_processes": 0,
    "gcs_client_connections": 1,
    "agent_client_connections": 1,
    "owner_serialize_threads": 0,
    "control_plane_io_lanes": False,
}

#: the "on" arm: the shipped defaults (completion batching, 64-task push
#: batches) plus 4 GCS shard processes fronted by the router and 2
#: parallel GCS connections.  Worker-connection lanes and the owner
#: serialization pool ship OFF by default: measured net-negative for
#: these workloads on a GIL interpreter (see ARCHITECTURE.md
#: "Horizontal control plane"), they exist for free-threaded builds and
#: multi-driver topologies.
CPSHARD_ON = {
    "gcs_shard_processes": 4,
    "gcs_client_connections": 2,
}


def _measure_cpshard(S: float, system_config: dict | None) -> dict:
    """One fresh-cluster measurement of the control-plane A/B metrics:
    tasks_async + pg_create_remove (the acceptance gates), a 50k-task
    drain (the scale proxy), and the fast paths that must NOT regress
    (get_small, put_gbps)."""
    import numpy as np

    import ray_tpu
    ray_tpu.init(num_cpus=8, object_store_memory=2 << 30,
                 _system_config=system_config or None)
    out = {}

    @ray_tpu.remote
    def noop(_x=None):
        return None

    try:
        ray_tpu.get([noop.remote() for _ in range(8)])
        n = int(1000 * S)
        out["tasks_async"] = max(timeit(
            lambda: ray_tpu.get([noop.remote() for _ in range(n)]), n))

        n = max(int(20 * S), 5)

        def pg_cycle():
            for _ in range(n):
                pg = ray_tpu.placement_group([{"CPU": 1}])
                pg.ready(timeout=30)
                ray_tpu.remove_placement_group(pg)

        out["pg_create_remove"] = max(timeit(pg_cycle, n))

        nd = int(50_000 * S)
        t0 = time.perf_counter()
        refs = [noop.remote() for _ in range(nd)]
        for i in range(0, nd, 10_000):
            ray_tpu.get(refs[i:i + 10_000], timeout=900)
        out["drain_tasks_per_s"] = round(nd / (time.perf_counter() - t0), 1)

        small = ray_tpu.put(np.zeros(16))
        n = int(2000 * S)
        out["get_small"] = max(timeit(
            lambda: [ray_tpu.get(small) for _ in range(n)], n))

        big = np.zeros(64 * 1024 * 1024, np.uint8)
        n = max(int(8 * S), 2)

        def put_big():
            for _ in range(n):
                ray_tpu.put(big)

        out["put_gbps"] = max(ops * big.nbytes / 1e9
                              for ops in timeit(put_big, n))
    finally:
        ray_tpu.shutdown()
    return out


def run_ab_cpshard(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: the horizontal control plane (GCS shard
    processes + completion batching + bigger push batches) vs the pre-PR
    single-process, single-lane plane (the ISSUE-13 acceptance gate)."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_cpshard(S, dict(CPSHARD_ON)))
        off_runs.append(_measure_cpshard(S, dict(CPSHARD_OFF)))
        print(f"# cpshard ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "on_config": CPSHARD_ON, "off_config": CPSHARD_OFF,
            "ratio_on_off": ratio,
            "vs_baseline_on": {
                k: round(med([r[k] for r in on_runs]) / BASELINE[k], 3)
                for k in on_runs[0] if k in BASELINE}}


#: the "off" arm of the native-submission-plane A/B: the exact pre-PR
#: owner hot loop — per-call TaskSpec ctor (no templates, no free-list
#: recycling), per-spec wire tuples (no packed frames / C encoder), full
#: 3-events-per-task trails, per-ref refcount locking restored via the
#: scalar paths' semantics (batch helpers remain but the knobs gate the
#: allocation/encode/event savings the tentpole added).
SUBMIT_PLANE_OFF = {"submit_plane_native_enabled": False,
                    "task_event_sample_n": 0,
                    "spec_freelist_max": 0}


def run_ab_submitplane(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: the native submission plane (slotted/
    pooled specs + packed C-encoded frames + sampled events) on vs off
    (the ISSUE-16 acceptance gate: >= 1.5x tasks_async)."""
    on_cfg = {"task_event_sample_n": 8}
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_submission(S, dict(on_cfg)))
        off_runs.append(_measure_submission(S, dict(SUBMIT_PLANE_OFF)))
        print(f"# submitplane ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "on_config": on_cfg, "off_config": SUBMIT_PLANE_OFF,
            "ratio_on_off": ratio}


def _chipspeed_jax():
    """Import jax for the chip-speed A/B: CPU backend, 8 forced host
    devices so the dp=4 collectives in parallel/zero.py are real (must
    run before the first jax import in this process)."""
    import os
    import sys
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    return jax


def _measure_chipspeed(S: float, arm: str, steps: int) -> dict:
    """One fresh-jit run of the tiny-config dp=4 CPU train loop for one
    knob combination (``arm``: '+'-joined subset of splash/quant/zero, or
    'off').  Fixed seed and fixed batch schedule so arms are comparable
    numerically, not just in time."""
    import numpy as np

    import jax.numpy as jnp
    from ray_tpu.models import config as mcfg
    from ray_tpu.parallel import (OptimizerSpec, init_sharded_state,
                                  init_zero_state, make_mesh, make_train_step)

    cfg = mcfg.tiny()
    if "splash" in arm:
        cfg = mcfg.TransformerConfig(
            **{**cfg.__dict__, "attention_impl": "splash"})
    mesh = make_mesh(4, dp=4, fsdp=1)
    spec = OptimizerSpec(total_steps=1000, warmup_steps=5)
    opt = spec.build()
    zero, quant = "zero" in arm, "quant" in arm
    if zero:
        state, sh = init_zero_state(cfg, mesh, spec)
    else:
        state, sh = init_sharded_state(cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt, sh, compute_dtype=jnp.float32,
                           grad_quant_enabled=quant,
                           zero_sharded_update=zero, opt_spec=spec)
    rng = np.random.RandomState(0)
    batches = [{"tokens": rng.randint(0, cfg.vocab_size,
                                      (8, cfg.max_seq_len + 1))}
               for _ in range(steps)]
    losses = []
    state, m = step(state, batches[0])  # compile step, untimed
    jax_block = jnp.asarray(m["total_loss"]).block_until_ready()
    losses.append(float(jax_block))
    t0 = time.perf_counter()
    for b in batches[1:]:
        state, m = step(state, b)
        losses.append(float(m["total_loss"]))  # forces the step
    wall = time.perf_counter() - t0
    return {"arm": arm, "steps_per_s": round((steps - 1) / wall, 2),
            "final_loss": round(losses[-1], 6),
            "opt_state_bytes": step.opt_state_bytes,
            "wire_int8": any(d == "int8" for _, d in step.collective_bytes),
            "_losses": losses}


def run_ab_chipspeed(S: float, pairs: int) -> dict:
    """Interleaved CPU A/B of the chip-speed knobs (ISSUE-20 gates):

    - numerics: the ZeRO-sharded arm's per-step losses allclose to the
      replicated arm (same seed/batches, fp32); the int8 quantized
      round-trip stays inside the analytical amax/254-per-rank bound;
      splash interpret-mode forward parity vs ops/flash_attention.
    - <= 5% no-TPU overhead discipline: ``attention_impl="splash"`` on a
      box with no usable kernel must fall back to an identical compiled
      graph — its steps/s within 5% of the off arm.

    The quant/zero arms change the computation by design, so they get
    numerics bounds, not overhead bounds; their steps/s ratios are
    recorded for the record only (CPU time is not the TPU win).
    """
    jax = _chipspeed_jax()
    if len(jax.devices()) < 4:
        return {"skipped": f"need >= 4 devices, have {len(jax.devices())}"}
    import jax.numpy as jnp

    steps = max(int(10 * S), 6)
    arms = ("off", "splash", "splash+quant+zero")
    runs = {a: [] for a in arms}
    for i in range(pairs):
        for a in arms:
            runs[a].append(_measure_chipspeed(S, a, steps))
        print(f"# chipspeed ab pair {i + 1}/{pairs}: " +
              " ".join(f"{a}={runs[a][-1]['steps_per_s']}/s" for a in arms),
              flush=True)

    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {a: round(med([r["steps_per_s"] for r in runs[a]])
                      / max(med([r["steps_per_s"] for r in runs["off"]]),
                            1e-9), 3)
             for a in arms if a != "off"}

    # numerics gate 1: ZeRO == replicated, step for step (one fresh run
    # each, same batch schedule as the timed arms)
    l_ref = runs["off"][0]["_losses"]
    l_zero = _measure_chipspeed(S, "zero", steps)["_losses"]
    zero_err = max(abs(a - b) / max(abs(a), 1e-9)
                   for a, b in zip(l_ref, l_zero))
    zero_ok = zero_err < 1e-5

    # numerics gate 2: int8 block round-trip inside amax/254 per element
    from ray_tpu.parallel.quant_collectives import (dequantize_int8_block,
                                                    quantize_int8_block)
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 4096), jnp.float32) * 8
    q, s = quantize_int8_block(x, block=256)
    back = dequantize_int8_block(q, s, block=256)
    amax = jnp.max(jnp.abs(x.reshape(64, 16, 256)), -1, keepdims=True)
    bound = jnp.broadcast_to(amax / 254.0 + 1e-7, (64, 16, 256))
    quant_ok = bool(jnp.all(jnp.abs(back - x).reshape(64, 16, 256) <= bound))
    quant_max_err = float(jnp.max(jnp.abs(back - x)))

    # numerics gate 3: splash interpret-mode forward parity (recorded even
    # though the timed splash arm falls back on the tiny head_dim)
    from ray_tpu.ops.splash_attention import splash_mha
    from ray_tpu.ops.flash_attention import flash_attention
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    qq = jax.random.normal(ks[0], (1, 256, 4, 128), jnp.float32)
    kk = jax.random.normal(ks[1], (1, 256, 2, 128), jnp.float32)
    vv = jax.random.normal(ks[2], (1, 256, 2, 128), jnp.float32)
    sp = splash_mha(qq, kk, vv, causal=True)
    splash_err = (float(jnp.max(jnp.abs(
        sp - flash_attention(qq, kk, vv, causal=True))))
        if sp is not None else None)
    splash_ok = splash_err is not None and splash_err < 1e-4

    overhead_ok = ratio["splash"] >= 0.95
    strip = lambda r: {k: v for k, v in r.items()  # noqa: E731
                       if k != "_losses"}
    return {"pairs_on": [strip(r) for r in runs["splash+quant+zero"]],
            "pairs_off": [strip(r) for r in runs["off"]],
            "pairs_splash_fallback": [strip(r) for r in runs["splash"]],
            "ratio_on_off": {"steps_per_s": ratio["splash+quant+zero"]},
            "gate": {"zero_allclose_rtol": 1e-5,
                     "zero_max_rel_err": round(zero_err, 9),
                     "zero_allclose": zero_ok,
                     "quant_max_err": round(quant_max_err, 6),
                     "quant_bounded": quant_ok,
                     "splash_fwd_max_err": splash_err,
                     "splash_parity": splash_ok,
                     "max_overhead": 0.05,
                     "splash_fallback_ratio": ratio["splash"],
                     "overhead_ok": overhead_ok,
                     "passed": bool(zero_ok and quant_ok and splash_ok
                                    and overhead_ok)}}


def run_profile_submit(S: float) -> dict:
    """Per-stage µs breakdown of one WARM submission: spec build / encode
    / events / refcount measured in isolation on live runtime objects,
    serialize+flush attributed from the owner histograms over a clean
    burst, plus the bare .remote() driver-thread p50 they decompose."""
    import ray_tpu
    from ray_tpu.core import common, sched_explain
    from ray_tpu.core.core_worker import global_worker
    from ray_tpu.core.ids import TaskID
    from ray_tpu.core.remote_function import serialize_args

    ray_tpu.init(num_cpus=8, object_store_memory=2 << 30,
                 _system_config={"sched_metrics_enabled": True})
    prof = {}

    @ray_tpu.remote
    def noop(_x=None):
        return None

    try:
        ray_tpu.get([noop.remote() for _ in range(8)])
        ray_tpu.get([noop.remote() for _ in range(500)])  # warm everything
        w = global_worker()
        k = max(int(2000 * S), 500)
        args_blob, _ = serialize_args((), {})
        tmpl = noop._spec_tmpl
        assert tmpl is not None, "warm template missing — submit plane off?"

        # stage: spec build (free-list pop + template slot copy)
        t0 = time.perf_counter()
        specs = [common.build_spec_from_template(
            tmpl, TaskID.from_random(), args_blob, None) for _ in range(k)]
        prof["spec_build_us"] = round((time.perf_counter() - t0) / k * 1e6, 3)

        # stage: encode (packed batch frame, warm templates, batch of 64)
        stub = type("C", (), {"_writer": object()})()
        batch = specs[:64]
        w.spec_encoder.encode_batch(stub, batch)  # deliver templates once
        reps = max(k // 64, 8)
        t0 = time.perf_counter()
        for _ in range(reps):
            w.spec_encoder.encode_batch(stub, batch)
        prof["encode_us"] = round(
            (time.perf_counter() - t0) / (reps * len(batch)) * 1e6, 3)

        # stage: task events (one SUBMITTED stamp per task, current
        # sampling config; buffers restored afterwards)
        saved = w._task_events
        w._task_events = []
        t0 = time.perf_counter()
        for s in specs:
            w.task_event(s, "SUBMITTED")
        prof["events_us"] = round((time.perf_counter() - t0) / k * 1e6, 3)
        w._task_events = saved
        for s in specs:
            w._submit_ts.pop(s.task_id, None)

        # stage: refcount (one-ref add+remove round trip, batched paths)
        from ray_tpu.core.ids import ObjectID
        rc = w.reference_counter
        oids = [ObjectID.for_task_return(s.task_id, 0) for s in specs]
        t0 = time.perf_counter()
        for oid in oids:
            rc.add_submitted_many((oid,))
            rc.remove_submitted_many(((oid, w.address),))
        prof["refcount_us"] = round((time.perf_counter() - t0) / k * 1e6, 3)

        # serialize+flush attribution over a clean burst (owner histograms)
        om = sched_explain.owner_metrics()

        def hist_totals(h):
            return (sum(h._sum.values()), sum(h._count.values()))

        s0, f0 = hist_totals(om["serialize"]), hist_totals(om["flush"])
        nb = int(1000 * S)
        t_sub = []
        refs = []
        t0 = time.perf_counter()
        for _ in range(nb):
            c0 = time.perf_counter()
            refs.append(noop.remote())
            t_sub.append(time.perf_counter() - c0)
        ray_tpu.get(refs)
        wall = time.perf_counter() - t0
        s1, f1 = hist_totals(om["serialize"]), hist_totals(om["flush"])
        prof["serialize_us_per_task"] = round((s1[0] - s0[0]) / nb * 1e6, 3)
        prof["flush_us_per_task"] = round((f1[0] - f0[0]) / nb * 1e6, 3)
        t_sub.sort()
        prof["bare_submit_us_p50"] = round(t_sub[len(t_sub) // 2] * 1e6, 3)
        prof["burst_tasks_per_s"] = round(nb / wall, 1)
        prof["note"] = ("spec_build/encode/events/refcount measured in "
                        "isolation on live objects; serialize/flush are "
                        "owner-histogram deltas over the burst; "
                        "bare_submit_us_p50 is the driver-thread .remote() "
                        "cost those stages decompose")
    finally:
        ray_tpu.shutdown()
    return prof


def run_ab_fastpath(S: float, pairs: int) -> dict:
    """Interleaved same-box A/B: fast path ON vs OFF, alternating fresh
    clusters so box drift lands evenly on both arms."""
    on_runs, off_runs = [], []
    for i in range(pairs):
        on_runs.append(_measure_submission(S, None))
        off_runs.append(_measure_submission(S, dict(FASTPATH_OFF)))
        print(f"# ab pair {i + 1}/{pairs}: on={on_runs[-1]} "
              f"off={off_runs[-1]}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    ratio = {k: round(med([r[k] for r in on_runs])
                      / max(med([r[k] for r in off_runs]), 1e-9), 3)
             for k in on_runs[0]}
    return {"pairs_on": on_runs, "pairs_off": off_runs,
            "off_config": FASTPATH_OFF, "ratio_on_off": ratio}


def main():
    global _REPS
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    p.add_argument("--scale", type=float, default=1.0,
                   help="shrink/grow iteration counts")
    p.add_argument("--serve", action="store_true",
                   help="include the Serve noop benchmark (slower)")
    p.add_argument("--runs", type=int, default=3,
                   help="repeat the whole suite N times (fresh cluster "
                        "each); with --reps samples per metric per run the "
                        "aggregate reports median + IQR + min per metric")
    p.add_argument("--reps", type=int, default=_REPS,
                   help="timed repetitions per metric within one suite pass")
    p.add_argument("--ab-fastpath", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of the "
                        "submission fast path (inlining + spec caching + "
                        "lease pipelining) on vs off and embed the ratios")
    p.add_argument("--ab-serve", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of "
                        "serve_metrics_enabled on vs off (serve request "
                        "throughput; the serve-observability overhead gate)")
    p.add_argument("--ab-specroute", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of "
                        "speculative decode + cache-aware routing on vs "
                        "dense decode + pure p2c over the same damped CPU "
                        "model and seeded shared-prefix traffic (the "
                        "spec-serving >= 1.3x gate)")
    p.add_argument("--ab-submit", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of batched "
                        "submission on vs off (push/lease/actor-call "
                        "batching; the scale-envelope gate)")
    p.add_argument("--ab-train-obs", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of "
                        "train_metrics_enabled on vs off (CPU train-loop "
                        "steps/s; the train-observability overhead gate)")
    p.add_argument("--ab-elastic", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS triples of a fixed train workload "
                        "under the same seeded mid-run preemption: elastic "
                        "resize vs restart-from-checkpoint vs undisturbed "
                        "baseline (the elastic-training recovery-cost gate)")
    p.add_argument("--ab-sched", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of "
                        "sched_metrics_enabled on vs off (tasks_async + "
                        "submit_burst; the scheduler-observability "
                        "overhead gate)")
    p.add_argument("--ab-cpshard", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of the "
                        "horizontal control plane (GCS shard processes + "
                        "completion batching) on vs the pre-PR "
                        "single-process single-lane plane")
    p.add_argument("--ab-zcput", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of the "
                        "zero-copy put + multi-socket adaptive transfer "
                        "plane on vs the prior 1-copy/fixed-chunk plane "
                        "(put GB/s, large get, 8-way arg fan-out)")
    p.add_argument("--ab-autoscale", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of the SLO "
                        "autoscaler policy on vs no autoscaling over a "
                        "steady noop deployment (the control-loop "
                        "overhead gate)")
    p.add_argument("--ab-submitplane", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of the "
                        "native submission plane (pooled specs + packed "
                        "C frames + sampled events) on vs off")
    p.add_argument("--ab-health", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of "
                        "health_metrics_enabled on vs off (submit_churn "
                        "+ serve_noop with hot detector cadences; the "
                        "health-plane overhead gate)")
    p.add_argument("--ab-chipspeed", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved CPU A/B triples of the "
                        "chip-speed knobs (splash attention / int8 grad "
                        "quant / ZeRO-sharded update) on vs off on a tiny "
                        "dp=4 config, gating numerics equivalence and the "
                        "<= 5% no-TPU fallback overhead")
    p.add_argument("--profile-submit", action="store_true",
                   help="profile one warm submission: per-stage µs "
                        "(spec build / encode / events / refcount / "
                        "serialize+flush) plus bare .remote() p50")
    p.add_argument("--ab-object", type=int, default=0, metavar="PAIRS",
                   help="also run PAIRS interleaved A/B pairs of "
                        "object_metrics_enabled on vs off (put GB/s, "
                        "large get, 8-way arg fan-out; the object-plane "
                        "observability overhead gate)")
    args = p.parse_args()
    _REPS = max(args.reps, 1)

    all_runs = []
    # --runs 0: skip the full suite (targeted A/B-only invocations)
    for r in range(args.runs):
        res = run_suite(args.scale, args.serve)
        all_runs.append(res)
        if args.runs > 1:
            print(f"# run {r + 1}/{args.runs}: "
                  f"{json.dumps({k: [round(x, 1) for x in v] for k, v in res.items()})}",
                  flush=True)

    def quantile(xs, q):
        xs = sorted(xs)
        i = (len(xs) - 1) * q
        lo, hi = int(i), min(int(i) + 1, len(xs) - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (i - lo)

    metrics = list(all_runs[0]) if all_runs else []
    samples = {k: [x for r in all_runs for x in r[k]] for k in metrics}
    med = {k: quantile(samples[k], 0.5) for k in metrics}
    iqr = {k: quantile(samples[k], 0.75) - quantile(samples[k], 0.25)
           for k in metrics}
    # Schema note: "results"/"iqr"/"vs_baseline" keep their PERF_r0X.json
    # meaning (median ops/s per metric); "min"/"samples_per_metric" are
    # additive so older rounds still diff cleanly.
    out = {"metric": "core_microbench", "unit": "ops/s",
           "runs": args.runs,
           "samples_per_metric": args.runs * max(args.reps, 1),
           "results": {k: round(v, 1) for k, v in med.items()},
           "iqr": {k: round(v, 1) for k, v in iqr.items()},
           "min": {k: round(min(samples[k]), 1) for k in metrics},
           "vs_baseline": {k: round(med[k] / BASELINE[k], 3)
                           for k in metrics if k in BASELINE}}
    if args.ab_fastpath > 0:
        out["fastpath_ab"] = run_ab_fastpath(args.scale, args.ab_fastpath)
    if args.ab_serve > 0:
        out["serve_metrics_ab"] = run_ab_serve_metrics(args.scale,
                                                       args.ab_serve)
    if args.ab_specroute > 0:
        out["specroute_ab"] = run_ab_specroute(args.scale,
                                               args.ab_specroute)
    if args.ab_submit > 0:
        out["submit_batching_ab"] = run_ab_submit_batching(args.scale,
                                                           args.ab_submit)
    if args.ab_train_obs > 0:
        out["train_obs_ab"] = run_ab_train_obs(args.scale,
                                               args.ab_train_obs)
    if args.ab_elastic > 0:
        out["elastic_ab"] = run_ab_elastic(args.scale, args.ab_elastic)
    if args.ab_sched > 0:
        out["sched_obs_ab"] = run_ab_sched_obs(args.scale, args.ab_sched)
    if args.ab_autoscale > 0:
        out["autoscale_ab"] = run_ab_autoscale(args.scale,
                                               args.ab_autoscale)
    if args.ab_object > 0:
        out["object_obs_ab"] = run_ab_object_obs(args.scale,
                                                 args.ab_object)
    if args.ab_health > 0:
        out["health_ab"] = run_ab_health(args.scale, args.ab_health)
    if args.ab_zcput > 0:
        out["zcput_ab"] = run_ab_zcput(args.scale, args.ab_zcput)
    if args.ab_submitplane > 0:
        out["submitplane_ab"] = run_ab_submitplane(args.scale,
                                                   args.ab_submitplane)
    if args.ab_chipspeed > 0:
        out["chipspeed_ab"] = run_ab_chipspeed(args.scale,
                                               args.ab_chipspeed)
    if args.profile_submit:
        out["submit_profile"] = run_profile_submit(args.scale)
    if args.ab_cpshard > 0:
        out["cpshard_ab"] = run_ab_cpshard(args.scale, args.ab_cpshard)
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
