"""LLM serving benchmark: req/s + TTFT through the Serve stack.

BASELINE.json's second north-star metric is "Serve req/s + p50 TTFT" for a
continuous-batching LLM deployment (config #4).  This drives the real stack:
HTTP-less handle path -> router -> replica actor -> LLMEngine (slot-scheduled
continuous batching, bucketed prefill, single compiled decode step) on the
local accelerator.

Prints ONE JSON line:
  {"metric": "serve_llm", "req_per_s": ..., "p50_ttft_ms": ...,
   "p99_ttft_ms": ..., "decode_tok_per_s": ...}

vs_baseline: the reference has no LLM server to compare against (SURVEY §2.7)
— the serving-stack overhead budget is the comparable: decode throughput
through the full serving stack should be within 20% of the engine-only rate.
vs_baseline = served_decode_tok_s / bare_engine_decode_tok_s; >= 0.8 passes.
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="llama-1b")
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--num-slots", type=int, default=16)
    p.add_argument("--max-len", type=int, default=512)
    args = p.parse_args()

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMEngine, llm_deployment

    # --- bare-engine baseline: same model/config, no serving stack.
    # vs_baseline below = served decode throughput / this number (the
    # serving-overhead ratio this file's docstring defines; the reference
    # has no LLM server to compare against, SURVEY §2.7).
    from ray_tpu.models import config as mcfg
    rng = random.Random(0)

    def prompt():
        n = rng.randint(args.prompt_len // 2, args.prompt_len)
        return [rng.randint(1, 1000) for _ in range(n)]

    eng = LLMEngine(mcfg.PRESETS[args.preset](), num_slots=args.num_slots,
                    max_len=args.max_len, buckets=(args.prompt_len,))
    list(eng.stream(prompt(), max_tokens=4))  # compile
    bare_tokens = 0
    bare_t0 = time.time()
    from ray_tpu.serve.llm import _FLUSH
    pending = [eng.submit(prompt(), max_tokens=args.max_tokens)
               for _ in range(args.num_slots * 2)]
    for req in pending:
        while True:
            item = req.out.get()
            if item is _FLUSH:
                break
            if isinstance(item, BaseException):
                raise item
            bare_tokens += 1
    bare_tok_s = bare_tokens / (time.time() - bare_t0)
    eng.shutdown()

    # Paged-engine probe (same workload through the block-table KV cache +
    # prefix caching): guarded — the primary serving metric must survive a
    # paged compile failure on an exotic backend.
    paged_tok_s = None
    peng = None
    try:
        peng = LLMEngine(mcfg.PRESETS[args.preset](),
                         num_slots=args.num_slots, max_len=args.max_len,
                         buckets=(args.prompt_len,), paged=True)
        list(peng.stream(prompt(), max_tokens=4))  # compile
        n = 0
        t0 = time.time()
        reqs = [peng.submit(prompt(), max_tokens=args.max_tokens)
                for _ in range(args.num_slots * 2)]
        for req in reqs:
            while True:
                item = req.out.get()
                if item is _FLUSH:
                    break
                if isinstance(item, BaseException):
                    raise item
                n += 1
        paged_tok_s = round(n / (time.time() - t0), 1)
    except Exception as e:  # noqa: BLE001 — report, don't fail the bench
        paged_tok_s = f"error: {type(e).__name__}: {e}"[:200]
    finally:
        if peng is not None:
            # always stop the decode thread: a leaked engine would compete
            # with the serve benchmark measured next
            peng.shutdown()

    ray_tpu.init(num_cpus=8)
    try:
        dep = llm_deployment(
            args.preset, num_slots=args.num_slots, max_len=args.max_len,
            max_concurrent_queries=256, health_check_timeout_s=600.0,
            engine_kwargs={"buckets": (args.prompt_len,),
                           "warmup_buckets": True})
        h = serve.run(dep, timeout_s=600)
        # warmup: compile prefill buckets + decode
        list(h.stream({"tokens": prompt(), "max_tokens": 4}))

        ttfts, latencies, tokens = [], [], [0]
        lock = threading.Lock()
        reqs_per_client = args.requests // args.clients

        def client():
            for _ in range(reqs_per_client):
                t0 = time.monotonic()
                first = None
                n = 0
                for _tok in h.stream({"tokens": prompt(),
                                      "max_tokens": args.max_tokens}):
                    if first is None:
                        first = time.monotonic() - t0
                    n += 1
                dt = time.monotonic() - t0
                with lock:
                    ttfts.append(first)
                    latencies.append(dt)
                    tokens[0] += n

        t0 = time.time()
        threads = [threading.Thread(target=client)
                   for _ in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0

        n_reqs = len(latencies)
        ttfts.sort()
        stats = h.stats.remote().result(timeout_s=60)
        print(json.dumps({
            "metric": "serve_llm_req_per_s",
            "value": round(n_reqs / wall, 2),
            "unit": "req/s",
            # served decode throughput as a fraction of the bare engine on
            # the same box — the serving-stack overhead ratio (>= 0.8 is the
            # budget; there is no reference LLM server, SURVEY 2.7)
            "vs_baseline": round((tokens[0] / wall) / max(bare_tok_s, 1e-9),
                                 3),
            "bare_engine_tok_per_s": round(bare_tok_s, 1),
            "paged_engine_tok_per_s": paged_tok_s,
            "p50_ttft_ms": round(ttfts[n_reqs // 2] * 1000, 1),
            "p99_ttft_ms": round(ttfts[min(n_reqs - 1,
                                           int(n_reqs * 0.99))] * 1000, 1),
            "decode_tok_per_s": round(tokens[0] / wall, 1),
            "model": args.preset,
            "clients": args.clients, "requests": n_reqs,
            "prompt_len": args.prompt_len, "max_tokens": args.max_tokens,
            "num_slots": args.num_slots,
            "engine_steps": stats["steps"],
        }))
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
