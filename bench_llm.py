"""LLM serving benchmark: dense vs paged KV cache through the Serve stack.

BASELINE.json's second north-star metric is "Serve req/s + p50 TTFT" for a
continuous-batching LLM deployment (config #4).  This drives the real stack:
HTTP-less handle path -> router -> replica actor -> LLMEngine (slot-scheduled
continuous batching, bucketed prefill, single compiled decode step) on the
local accelerator, THREE times over the same long-prompt mix:

  1. dense  — slots x max_len KV rows (the r2 configuration)
  2. paged  — block-table KV pages (models/paged_decode.py)
  3. paged + shared-prefix workload — every prompt shares a long common
     prefix, so prefill hits the refcounted prefix cache

Prints ONE JSON line.  vs_baseline = paged req/s / dense req/s on the same
mix (>= 1.0 means paging pays for itself; the reference has no LLM server to
compare against, SURVEY §2.7).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import threading
import time

#: Schema contract for one configuration's per-request breakdown — the
#: full serving picture (open item #2) captured in one run.  Guarded by
#: tests/test_serve_observability.py::test_bench_llm_breakdown_schema so a
#: refactor cannot silently drop a field between chip windows.
REQUEST_KEYS = frozenset({
    "req_per_s", "n_requests", "decode_tok_per_s",
    "p50_ttft_ms", "p95_ttft_ms", "p99_ttft_ms",
    "p50_tpot_ms", "p95_tpot_ms",
})
#: engine-side breakdown keys (LLMEngine.breakdown(), via LLMServer.stats)
ENGINE_KEYS = frozenset({
    "admit_batches", "batch_occupancy", "padding_fraction",
})


def request_rollup(samples, wall_s: float) -> dict:
    """Per-request metrics rollup: ``samples`` is a list of
    ``(ttft_s, latency_s, n_tokens)`` tuples; returns the REQUEST_KEYS
    dict.  Pure — the schema-guard test drives it with synthetic
    samples.  TPOT = (latency - ttft) / (n_tokens - 1): steady-state
    decode pace after the first token."""
    n = len(samples)
    if not n:
        raise ValueError("no request samples")
    ttfts = sorted(s[0] for s in samples)
    tpots = sorted((lat - ttft) / (nt - 1)
                   for ttft, lat, nt in samples if nt > 1)

    def pct(xs, q):
        return xs[min(len(xs) - 1, int(len(xs) * q))] if xs else None

    rnd = lambda v: None if v is None else round(v * 1000, 2)  # noqa: E731
    return {
        "req_per_s": round(n / wall_s, 2),
        "n_requests": n,
        "decode_tok_per_s": round(sum(s[2] for s in samples) / wall_s, 1),
        "p50_ttft_ms": rnd(pct(ttfts, 0.50)),
        "p95_ttft_ms": rnd(pct(ttfts, 0.95)),
        "p99_ttft_ms": rnd(pct(ttfts, 0.99)),
        "p50_tpot_ms": rnd(pct(tpots, 0.50)),
        "p95_tpot_ms": rnd(pct(tpots, 0.95)),
    }


class PhaseAborted(RuntimeError):
    """One configuration failed to become servable; carries the
    controller's view of why (per-replica states) so the checkpoint
    records a diagnosable reason instead of a bare timeout."""

    def __init__(self, msg: str, detail: dict):
        super().__init__(msg)
        self.detail = detail


def probe_devices(timeout_s: float = 120.0):
    """Bounded accelerator probe in a SUBPROCESS.  A wedged TPU tunnel
    makes ``jax.devices()`` hang forever *in-process* — the round-4/5
    failure mode where the whole benchmark (and its collected numbers)
    died with the probe.  A child process gives us a kill switch; the
    parent never imports jax.  Returns None when healthy, else a short
    skip reason for the structured ``{"skipped": ...}`` exit."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return "tunnel wedged"
    if out.returncode != 0:
        tail = (out.stderr or out.stdout or "").strip().splitlines()
        return "probe failed: " + (tail[-1] if tail else "no output")
    return None


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="llama-1b")
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt-len", type=int, default=256,
                   help="max prompt length in the mix (min is 1/4 of this)")
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--num-slots", type=int, default=16)
    p.add_argument("--max-len", type=int, default=1024)
    p.add_argument("--storm", action="store_true",
                   help="add an open-loop arrival-spike phase (paged "
                        "config, serve/loadgen burst schedule + "
                        "heavy-tailed prompt lengths): how TTFT behaves "
                        "through a burst at fixed chip capacity")
    p.add_argument("--storm-rate", type=float, default=2.0,
                   help="storm base arrivals/s (spike is 4x)")
    p.add_argument("--deploy-timeout", type=float, default=300.0,
                   help="seconds to wait for a configuration's replica to "
                        "go HEALTHY before aborting that phase (the old "
                        "blind 900 s wait is gone: we poll serve.status() "
                        "and record the stuck replica's state instead)")
    p.add_argument("--probe-timeout", type=float, default=120.0,
                   help="subprocess jax.devices() probe bound")
    p.add_argument("--fresh", action="store_true",
                   help="ignore an existing BENCH_LLM_partial.json instead "
                        "of resuming from its checkpointed phases")
    args = p.parse_args()

    # Accelerator probe BEFORE touching the cluster: a wedged tunnel must
    # produce a structured skip (the driver keys on it), not a hang.
    reason = probe_devices(args.probe_timeout)
    if reason is not None:
        print(json.dumps({"metric": "serve_llm_req_per_s",
                          "skipped": reason}))
        return

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import llm_deployment

    rng = random.Random(0)
    buckets = (args.prompt_len // 4, args.prompt_len // 2, args.prompt_len)

    def mixed_prompt():
        """Long-prompt mix: lengths spread across all prefill buckets."""
        n = rng.randint(args.prompt_len // 4, args.prompt_len)
        return [rng.randint(1, 1000) for _ in range(n)]

    _prefix = [rng.randint(1, 1000) for _ in range(args.prompt_len - 32)]

    def prefix_prompt():
        """Shared-prefix workload: identical long prefix + short unique tail
        (multi-turn / system-prompt shape; hits the paged prefix cache)."""
        return _prefix + [rng.randint(1, 1000) for _ in range(32)]

    def drive(handle, make_prompt):
        """Run the client fleet; returns the REQUEST_KEYS breakdown."""
        samples = []  # (ttft_s, latency_s, n_tokens) per request
        lock = threading.Lock()
        reqs_per_client = args.requests // args.clients

        def client():
            for _ in range(reqs_per_client):
                t0 = time.monotonic()
                first, n = None, 0
                for _tok in handle.stream({"tokens": make_prompt(),
                                           "max_tokens": args.max_tokens}):
                    if first is None:
                        first = time.monotonic() - t0
                    n += 1
                with lock:
                    samples.append((first, time.monotonic() - t0, n))

        t0 = time.time()
        threads = [threading.Thread(target=client)
                   for _ in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return request_rollup(samples, time.time() - t0)

    def drive_storm(handle):
        """Open-loop burst phase (serve/loadgen): arrivals fire on a
        seeded schedule regardless of completion pace, so queueing delay
        shows in TTFT instead of slowing the client.  Heavy-tailed
        prompt/decode lengths stress the prefill buckets + paged KV the
        way production traffic would."""
        from ray_tpu.serve import loadgen

        srng = random.Random(1)
        warm_s, spike_s, cool_s = 5.0, 10.0, 5.0
        total = warm_s + spike_s + cool_s
        arrivals = loadgen.burst_arrivals(
            args.storm_rate, 4.0, warm_s, warm_s + spike_s, total, srng)

        def payload(idx: int):
            return loadgen.llm_payload(
                1, idx, prompt_median=args.prompt_len // 2,
                prompt_lo=args.prompt_len // 4, prompt_hi=args.prompt_len,
                decode_median=args.max_tokens // 2,
                decode_hi=args.max_tokens)

        runner = loadgen.StormRunner(
            loadgen.stream_fire(handle, payload, timeout_s=600.0),
            max_outstanding=256)
        t0 = time.time()
        storm_samples = runner.run(arrivals)
        wall = time.time() - t0
        ok = [s.rollup_tuple() for s in storm_samples if s.ok]
        out = request_rollup(ok, wall) if ok else {"n_requests": 0}
        out["n_errors"] = sum(1 for s in storm_samples if not s.ok)
        out["arrivals"] = loadgen.arrival_rate_series(arrivals)
        out["ttft_p95_series"] = loadgen.windowed_p95_series(storm_samples)
        return out

    def wait_servable(name: str, timeout_s: float):
        """Poll serve.status() until ``name`` is HEALTHY.  The old path
        blocked 900 s inside serve.run with zero visibility — when a
        replica wedged in STARTING (phase-3 failure mode) the whole run
        burned its budget and reported nothing.  On timeout, raise with
        the controller's per-replica states so the checkpoint says WHY."""
        deadline = time.monotonic() + timeout_s
        last: dict = {}
        while time.monotonic() < deadline:
            try:
                last = serve.status().get(name, {})
            except Exception as e:  # noqa: BLE001 — controller booting
                last = {"error": repr(e)}
            if last.get("status") == "HEALTHY":
                return
            time.sleep(2.0)
        pending = [{"name": r.get("name"), "state": r.get("state")}
                   for r in last.get("replicas", [])]
        raise PhaseAborted(
            f"{name} not HEALTHY after {timeout_s:.0f}s",
            {"status": last.get("status"), "replicas": pending,
             **({"error": last["error"]} if "error" in last else {})})

    def run_serve(paged: bool, make_prompt, label: str,
                  storm: bool = False, extra_engine: dict | None = None):
        """One full cluster lifecycle per configuration: the TPU is held
        exclusively by the replica process, so the next configuration's
        replica can only initialize after a complete teardown."""
        print(f"# {label}: deploying…", flush=True)
        ray_tpu.init(num_cpus=8)
        try:
            dep = llm_deployment(
                args.preset, num_slots=args.num_slots, max_len=args.max_len,
                max_concurrent_queries=256, health_check_timeout_s=600.0,
                engine_kwargs={"buckets": buckets, "warmup_buckets": True,
                               "paged": paged, **(extra_engine or {})})
            h = serve.run(dep, timeout_s=args.deploy_timeout,
                          _blocking=False)
            wait_servable(f"llm-{args.preset}", args.deploy_timeout)
            list(h.stream({"tokens": make_prompt(), "max_tokens": 4}))
            res = drive_storm(h) if storm else drive(h, make_prompt)
            # engine-side serving picture: batch occupancy/padding waste,
            # KV page utilization, prefix-cache hit rate (LLMServer.stats
            # -> LLMEngine.breakdown)
            try:
                res["engine"] = h.stats.remote().result(timeout_s=60)
            except Exception as e:  # noqa: BLE001 — breakdown is additive
                res["engine"] = {"error": repr(e)}
            return res
        finally:
            try:
                serve.shutdown()
            except Exception:
                pass
            ray_tpu.shutdown()
            time.sleep(20)  # let the replica process release the chip
            # (the tunnel-side lock can take O(10s) to clear after the
            # worker exits; 5 s proved too short in the round-5 run)

    # Resume from the checkpoint file: a re-run after a mid-bench tunnel
    # death replays only the missing phases (each phase persists its
    # numbers the moment it completes).  --fresh starts over.
    partial = {}
    if not args.fresh and os.path.exists("BENCH_LLM_partial.json"):
        try:
            with open("BENCH_LLM_partial.json") as f:
                partial = json.load(f)
            done = [k for k, v in partial.items()
                    if not (isinstance(v, dict) and "aborted" in v)]
            if done:
                print(f"# resuming: phases {done} checkpointed, skipping",
                      flush=True)
        except Exception:  # noqa: BLE001 — corrupt checkpoint: start over
            partial = {}

    def phase(key, *a, **kw):
        """Run one configuration and persist its numbers IMMEDIATELY — a
        later phase wedging the TPU tunnel must not lose earlier results
        (the round-4/5 lesson: phase 3 hung for 900 s and phases 1-2's
        numbers evaporated with it).  A checkpointed phase is skipped on
        resume; an aborted one (deploy never went HEALTHY) records its
        reason and re-runs next time."""
        cached = partial.get(key)
        if isinstance(cached, dict) and "aborted" not in cached:
            print(f"# {key}: checkpointed, skipping", flush=True)
            return cached
        try:
            res = run_serve(*a, **kw)
        except PhaseAborted as e:
            res = {"aborted": str(e), **e.detail}
        partial[key] = res
        print(f"# {key}: {json.dumps(res)}", flush=True)
        with open("BENCH_LLM_partial.json", "w") as f:
            json.dump(partial, f, indent=1)
        if "aborted" in res:
            # a wedged tunnel poisons every later phase too — probe, and
            # bail out structured (checkpoint keeps what we have)
            reason = probe_devices(args.probe_timeout)
            if reason is not None:
                print(json.dumps({"metric": "serve_llm_req_per_s",
                                  "skipped": reason, "partial": partial}))
                raise SystemExit(0)
        return res

    def ok(res):
        return isinstance(res, dict) and "aborted" not in res \
            and "req_per_s" in res

    try:
        dense = phase("dense", False, mixed_prompt, "dense")
        paged = phase("paged", True, mixed_prompt, "paged")
        prefix = phase("paged_prefix", True, prefix_prompt, "paged+prefix")
        # speculative decoding under the same continuous-batching paged
        # config: 1-layer draft, verify-window target step (the PR-19
        # serving path; acceptance + rollback stats land in res["engine"])
        spec = phase("paged_spec", True, mixed_prompt, "paged+spec",
                     extra_engine={"spec_decode_enabled": True, "spec_k": 4,
                                   "spec_draft_layers": 1})
        storm = None
        if args.storm:
            # checkpointed like every phase: a tunnel death after the
            # headline numbers must not lose them
            storm = phase("storm", True, mixed_prompt, "storm", True)
        out = {
            "metric": "serve_llm_req_per_s",
            "value": paged.get("req_per_s"),
            "unit": "req/s",
            "dense": dense,
            "paged": paged,
            "paged_prefix_hit": prefix,
            "paged_spec": spec,
            **({"storm": storm} if storm is not None else {}),
            "model": args.preset,
            "clients": args.clients, "requests": args.requests,
            "prompt_mix": [args.prompt_len // 4, args.prompt_len],
            "max_tokens": args.max_tokens,
            "num_slots": args.num_slots, "max_len": args.max_len,
        }
        if ok(dense) and ok(paged):
            # paging must at least match dense on the same long-prompt mix
            out["vs_baseline"] = round(
                paged["req_per_s"] / max(dense["req_per_s"], 1e-9), 3)
        if ok(paged) and ok(spec):
            out["spec_vs_paged"] = round(
                spec["decode_tok_per_s"]
                / max(paged["decode_tok_per_s"], 1e-9), 3)
        print(json.dumps(out))
    finally:
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()


if __name__ == "__main__":
    main()
