"""Scale-envelope benchmark — the million-task proof point.

Produces the queue-depth curve the ROADMAP asks for: for each depth N,
submit N no-arg tasks from one driver (through the admission gate) and
drain them, recording

* drain throughput (tasks/s over the whole submit+drain wall clock),
* p50/p99 of the bare ``.remote()`` submission call (gate waits included
  — at depths past ``submit_inflight_limit`` the p99 IS the pipelining
  behavior, not a defect),
* peak RSS and RSS delta of the driver process,
* admission-gate park count and the owner's shed-event count.

Also cycles placement groups (create→ready→remove) and churns actors
(create→ping→kill in waves) to exercise the other two envelope axes.

Run: ``python bench_scale.py [--depths 10000,100000,1000000]
[--pg-cycles 1000] [--actors 1000] [--out BENCH_SCALE.json]``

Each depth runs on a FRESH cluster so retained state from one depth
cannot subsidize (or poison) the next.
"""

from __future__ import annotations

import argparse
import gc
import json
import time

from ray_tpu.util.procmem import PeakRssSampler, rss_mb


def _pctl(sorted_xs, q):
    return sorted_xs[min(len(sorted_xs) - 1, int(len(sorted_xs) * q))]


def _saturation_snapshot() -> dict:
    """Control-plane saturation rollup recorded per depth point: which
    loop is how busy, the top GCS handlers by cumulative busy seconds,
    and the backpressure-reject counts — the before-curve the
    control-plane sharding work (ROADMAP item 5) will be judged
    against.  Best effort: a missing piece records as None, never
    fails the bench."""
    out: dict = {}
    try:
        from ray_tpu.core.api import _state
        from ray_tpu.core.core_worker import global_worker
        from ray_tpu.core.rpc import run_async
        w = global_worker()
        stats = run_async(w.gcs.call("sched_stats"), timeout=30)
        out["gcs_loop_busy_fraction"] = stats.get("loop_busy_fraction")
        # horizontal control plane: per-shard-process busy fractions
        # (process="gcs_shard:<i>") — the "is the load actually
        # spreading" series the shard curve is judged by
        if stats.get("shard_busy_fractions"):
            out["shard_busy_fractions"] = stats["shard_busy_fractions"]
        out["gcs_top_handlers"] = [
            [m, round(s, 3)] for m, s in (stats.get("top_handlers")
                                          or [])[:3]]
        out["gcs_handler_calls_top"] = {
            m: stats.get("handler_calls", {}).get(m)
            for m, _s in (stats.get("top_handlers") or [])[:3]}
        mon = getattr(w, "_loop_monitor", None)
        out["owner_loop_busy_fraction"] = getattr(mon, "busy_fraction",
                                                  None)
        agent = getattr(_state, "node_agent", None)
        if agent is not None:
            amon = getattr(agent, "_loop_monitor", None)
            out["agent_loop_busy_fraction"] = getattr(
                amon, "busy_fraction", None)
            out["backpressure_rejects"] = dict(agent._bp_rejects)
    except Exception as e:  # noqa: BLE001 — observability must not wedge
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def bench_depth(depth: int, system_config: dict | None = None) -> dict:
    import ray_tpu
    from ray_tpu.core.core_worker import global_worker

    ray_tpu.init(num_cpus=8, object_store_memory=1 << 30,
                 _system_config=dict(system_config) if system_config else None)
    out: dict = {"depth": depth}
    if system_config:
        out["system_config"] = dict(system_config)
    try:
        @ray_tpu.remote
        def inc(x):
            return x + 1

        ray_tpu.get([inc.remote(0) for _ in range(8)])  # warm the pool
        gc.collect()
        rss0 = rss_mb()
        sampler = PeakRssSampler()
        t_sub = []
        t0 = time.perf_counter()
        refs = []
        for i in range(depth):
            s0 = time.perf_counter()
            refs.append(inc.remote(i))
            t_sub.append(time.perf_counter() - s0)
        t_submitted = time.perf_counter()
        total, count = 0, 0
        for i in range(0, depth, 10_000):
            chunk = ray_tpu.get(refs[i:i + 10_000], timeout=1800)
            count += len(chunk)
            total += sum(chunk)
        t1 = time.perf_counter()
        peak = sampler.stop()
        assert count == depth and total == depth * (depth + 1) // 2
        w = global_worker()
        t_sub.sort()
        out.update({
            "drained": count,
            "submit_s": round(t_submitted - t0, 2),
            "total_s": round(t1 - t0, 2),
            "drain_tasks_per_s": round(depth / (t1 - t0), 1),
            "submit_us_p50": round(_pctl(t_sub, 0.50) * 1e6, 1),
            "submit_us_p99": round(_pctl(t_sub, 0.99) * 1e6, 1),
            "peak_rss_mb": round(peak, 1),
            "rss_delta_mb": round(peak - rss0, 1),
            "gate_parks": w.admission_gate.blocked_total,
            "events_shed": w.task_events_shed_total,
            # saturation series: sampled at the END of the drain, while
            # the busy-fraction windows still reflect steady state
            "saturation": _saturation_snapshot(),
        })
        # what the health plane made of the drain (EVENTS_SHED /
        # GCS_HANDLER_HOT raises land here when the depth provokes them)
        from ray_tpu.util import health
        out["health"] = health.alert_trail()
    finally:
        ray_tpu.shutdown()
    return out


def bench_pg_cycles(cycles: int) -> dict:
    import ray_tpu
    ray_tpu.init(num_cpus=8)
    try:
        # warm
        pg = ray_tpu.placement_group([{"CPU": 0.01}])
        pg.ready(timeout=30)
        ray_tpu.remove_placement_group(pg)
        t0 = time.perf_counter()
        for _ in range(cycles):
            pg = ray_tpu.placement_group([{"CPU": 0.01}])
            pg.ready(timeout=30)
            ray_tpu.remove_placement_group(pg)
        dt = time.perf_counter() - t0
        return {"cycles": cycles, "total_s": round(dt, 2),
                "cycles_per_s": round(cycles / dt, 1)}
    finally:
        ray_tpu.shutdown()


def bench_actor_churn(total: int, wave: int = 50) -> dict:
    import ray_tpu
    ray_tpu.init(num_cpus=8)
    try:
        @ray_tpu.remote(num_cpus=0)
        class A:
            def ping(self):
                return 1

        done = 0
        t0 = time.perf_counter()
        while done < total:
            n = min(wave, total - done)
            actors = [A.remote() for _ in range(n)]
            ray_tpu.get([a.ping.remote() for a in actors], timeout=600)
            for a in actors:
                ray_tpu.kill(a)
            done += n
        dt = time.perf_counter() - t0
        return {"actors": total, "wave": wave, "total_s": round(dt, 2),
                "actors_per_s": round(total / dt, 1)}
    finally:
        ray_tpu.shutdown()


_CP_CLIENT_SRC = """
import json, sys, time
from ray_tpu.core.gcs_router import ShardedGcsClient
from ray_tpu.core.rpc import run_async

addr, ops = sys.argv[1], int(sys.argv[2])
cli = ShardedGcsClient(addr, identity=f"bench-{{pid}}".format(pid=__import__('os').getpid()))
res = run_async(cli.call("get_shard_map"))
cli.apply_shard_map(res)
run_async(cli.call("ping"))  # connections warm
events = [{"task_id": f"t{i}", "name": "cp", "state": "FINISHED",
           "ts": time.time()} for i in range(100)]
t0 = time.perf_counter()

async def drive():
    import asyncio
    window = 128  # pipelined in-flight ops: saturate the server, not RTT
    for j0 in range(0, ops, window):
        await asyncio.gather(*[
            cli.call_retry("kv_put", ns=f"ns{j % 509}", key=f"k{j % 64}",
                           value=b"x" * 64)
            for j in range(j0, min(j0 + window, ops))])
        await cli.call("add_task_events", events=events)

run_async(drive())
dt = time.perf_counter() - t0
run_async(cli.close())
print(json.dumps({"ops": ops, "s": dt}))
"""


def bench_control_plane(shards: int, clients: int = 16,
                        ops: int = 3000) -> dict:
    """Control-plane saturation at N shard processes: ``clients`` REAL
    client processes hammer the sharded KV (+ task-event fan-in batches)
    concurrently; reported throughput is aggregate acked ops/s.  This is
    the axis the multi-process GCS exists for — server-side work spreads
    over shard processes (cores), so throughput should grow with the
    shard count while per-shard busy fractions stay < 1.0."""
    import os
    import subprocess
    import sys

    from ray_tpu.core.config import Config, reset_config, set_config
    from ray_tpu.core.gcs import GcsServer
    from ray_tpu.core.rpc import run_async

    set_config(Config(gcs_shard_processes=shards))
    gcs = GcsServer()
    run_async(gcs.start(), timeout=120)
    try:
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.abspath(__file__))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        t0 = time.perf_counter()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CP_CLIENT_SRC, gcs.address, str(ops)],
            stdout=subprocess.PIPE, env=env) for _ in range(clients)]
        outs = [json.loads(p.stdout.read().decode().strip().splitlines()[-1])
                for p in procs]
        for p in procs:
            p.wait()
        wall = time.perf_counter() - t0
        stats = run_async(gcs.handle_sched_stats())
        total_ops = sum(o["ops"] for o in outs)
        return {
            "shards": shards,
            "clients": clients,
            "kv_ops_total": total_ops,
            "wall_s": round(wall, 2),
            "kv_ops_per_s": round(total_ops / wall, 1),
            "router_busy_fraction": stats.get("loop_busy_fraction"),
            "shard_busy_fractions": stats.get("shard_busy_fractions"),
        }
    finally:
        run_async(gcs.stop(), timeout=10)
        reset_config()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--depths", default="10000,100000,1000000",
                   help="comma-separated queue depths for the task curve")
    p.add_argument("--pg-cycles", type=int, default=1000)
    p.add_argument("--actors", type=int, default=1000)
    p.add_argument("--shard-curve", default="",
                   help="comma-separated GCS shard-process counts (e.g. "
                        "1,2,4): per count, run a drain at --shard-depth "
                        "AND a multi-client control-plane saturation bench")
    p.add_argument("--shard-depth", type=int, default=200_000,
                   help="drain depth for each --shard-curve point")
    p.add_argument("--sample-n", type=int, default=8,
                   help="after the default-config curve, rerun the deepest "
                        "drain with task_event_sample_n=N — the at-scale "
                        "event-sampling config (0/1 skips the extra point)")
    p.add_argument("--out", default=None)
    args = p.parse_args()

    from ray_tpu.core.config import Config
    cfg = Config()
    out = {
        "metric": "scale_envelope",
        "config": {
            "submit_inflight_limit": cfg.submit_inflight_limit,
            "submit_batching_enabled": cfg.submit_batching_enabled,
            "lease_queue_max_depth": cfg.lease_queue_max_depth,
            "gcs_table_shards": cfg.gcs_table_shards,
            "sched_metrics_enabled": cfg.sched_metrics_enabled,
        },
        "task_curve": [],
    }
    depths = [int(x) for x in args.depths.split(",") if x.strip()]
    for d in depths:
        res = bench_depth(d)
        out["task_curve"].append(res)
        print(f"# depth {d}: {json.dumps(res)}", flush=True)
    if args.sample_n > 1 and depths:
        # the deepest drain is GCS event-ingest bound with full trails:
        # record the same point under the at-scale sampling config so the
        # curve shows what payload sampling buys (counters stay exact;
        # terminals still emit — see ARCHITECTURE.md "Native submission
        # plane")
        d = max(depths)
        res = bench_depth(
            d, system_config={"task_event_sample_n": args.sample_n})
        out["task_curve"].append(res)
        print(f"# depth {d} (sample_n={args.sample_n}): {json.dumps(res)}",
              flush=True)
    shard_counts = [int(x) for x in args.shard_curve.split(",") if x.strip()]
    if shard_counts:
        out["shard_curve"] = []
        for n in shard_counts:
            point = {"shards": n}
            point["drain"] = bench_depth(
                args.shard_depth, system_config={"gcs_shard_processes": n,
                                                 "gcs_client_connections": 2})
            point["control_plane"] = bench_control_plane(n)
            out["shard_curve"].append(point)
            print(f"# shards {n}: {json.dumps(point)}", flush=True)
    if args.pg_cycles > 0:
        out["pg_cycles"] = bench_pg_cycles(args.pg_cycles)
        print(f"# pg: {json.dumps(out['pg_cycles'])}", flush=True)
    if args.actors > 0:
        out["actor_churn"] = bench_actor_churn(args.actors)
        print(f"# actors: {json.dumps(out['actor_churn'])}", flush=True)
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
