"""Scale-envelope benchmark — the million-task proof point.

Produces the queue-depth curve the ROADMAP asks for: for each depth N,
submit N no-arg tasks from one driver (through the admission gate) and
drain them, recording

* drain throughput (tasks/s over the whole submit+drain wall clock),
* p50/p99 of the bare ``.remote()`` submission call (gate waits included
  — at depths past ``submit_inflight_limit`` the p99 IS the pipelining
  behavior, not a defect),
* peak RSS and RSS delta of the driver process,
* admission-gate park count and the owner's shed-event count.

Also cycles placement groups (create→ready→remove) and churns actors
(create→ping→kill in waves) to exercise the other two envelope axes.

Run: ``python bench_scale.py [--depths 10000,100000,1000000]
[--pg-cycles 1000] [--actors 1000] [--out BENCH_SCALE.json]``

Each depth runs on a FRESH cluster so retained state from one depth
cannot subsidize (or poison) the next.
"""

from __future__ import annotations

import argparse
import gc
import json
import time

from ray_tpu.util.procmem import PeakRssSampler, rss_mb


def _pctl(sorted_xs, q):
    return sorted_xs[min(len(sorted_xs) - 1, int(len(sorted_xs) * q))]


def _saturation_snapshot() -> dict:
    """Control-plane saturation rollup recorded per depth point: which
    loop is how busy, the top GCS handlers by cumulative busy seconds,
    and the backpressure-reject counts — the before-curve the
    control-plane sharding work (ROADMAP item 5) will be judged
    against.  Best effort: a missing piece records as None, never
    fails the bench."""
    out: dict = {}
    try:
        from ray_tpu.core.api import _state
        from ray_tpu.core.core_worker import global_worker
        from ray_tpu.core.rpc import run_async
        w = global_worker()
        stats = run_async(w.gcs.call("sched_stats"), timeout=30)
        out["gcs_loop_busy_fraction"] = stats.get("loop_busy_fraction")
        out["gcs_top_handlers"] = [
            [m, round(s, 3)] for m, s in (stats.get("top_handlers")
                                          or [])[:3]]
        out["gcs_handler_calls_top"] = {
            m: stats.get("handler_calls", {}).get(m)
            for m, _s in (stats.get("top_handlers") or [])[:3]}
        mon = getattr(w, "_loop_monitor", None)
        out["owner_loop_busy_fraction"] = getattr(mon, "busy_fraction",
                                                  None)
        agent = getattr(_state, "node_agent", None)
        if agent is not None:
            amon = getattr(agent, "_loop_monitor", None)
            out["agent_loop_busy_fraction"] = getattr(
                amon, "busy_fraction", None)
            out["backpressure_rejects"] = dict(agent._bp_rejects)
    except Exception as e:  # noqa: BLE001 — observability must not wedge
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def bench_depth(depth: int) -> dict:
    import ray_tpu
    from ray_tpu.core.core_worker import global_worker

    ray_tpu.init(num_cpus=8, object_store_memory=1 << 30)
    out: dict = {"depth": depth}
    try:
        @ray_tpu.remote
        def inc(x):
            return x + 1

        ray_tpu.get([inc.remote(0) for _ in range(8)])  # warm the pool
        gc.collect()
        rss0 = rss_mb()
        sampler = PeakRssSampler()
        t_sub = []
        t0 = time.perf_counter()
        refs = []
        for i in range(depth):
            s0 = time.perf_counter()
            refs.append(inc.remote(i))
            t_sub.append(time.perf_counter() - s0)
        t_submitted = time.perf_counter()
        total, count = 0, 0
        for i in range(0, depth, 10_000):
            chunk = ray_tpu.get(refs[i:i + 10_000], timeout=1800)
            count += len(chunk)
            total += sum(chunk)
        t1 = time.perf_counter()
        peak = sampler.stop()
        assert count == depth and total == depth * (depth + 1) // 2
        w = global_worker()
        t_sub.sort()
        out.update({
            "drained": count,
            "submit_s": round(t_submitted - t0, 2),
            "total_s": round(t1 - t0, 2),
            "drain_tasks_per_s": round(depth / (t1 - t0), 1),
            "submit_us_p50": round(_pctl(t_sub, 0.50) * 1e6, 1),
            "submit_us_p99": round(_pctl(t_sub, 0.99) * 1e6, 1),
            "peak_rss_mb": round(peak, 1),
            "rss_delta_mb": round(peak - rss0, 1),
            "gate_parks": w.admission_gate.blocked_total,
            "events_shed": w.task_events_shed_total,
            # saturation series: sampled at the END of the drain, while
            # the busy-fraction windows still reflect steady state
            "saturation": _saturation_snapshot(),
        })
    finally:
        ray_tpu.shutdown()
    return out


def bench_pg_cycles(cycles: int) -> dict:
    import ray_tpu
    ray_tpu.init(num_cpus=8)
    try:
        # warm
        pg = ray_tpu.placement_group([{"CPU": 0.01}])
        pg.ready(timeout=30)
        ray_tpu.remove_placement_group(pg)
        t0 = time.perf_counter()
        for _ in range(cycles):
            pg = ray_tpu.placement_group([{"CPU": 0.01}])
            pg.ready(timeout=30)
            ray_tpu.remove_placement_group(pg)
        dt = time.perf_counter() - t0
        return {"cycles": cycles, "total_s": round(dt, 2),
                "cycles_per_s": round(cycles / dt, 1)}
    finally:
        ray_tpu.shutdown()


def bench_actor_churn(total: int, wave: int = 50) -> dict:
    import ray_tpu
    ray_tpu.init(num_cpus=8)
    try:
        @ray_tpu.remote(num_cpus=0)
        class A:
            def ping(self):
                return 1

        done = 0
        t0 = time.perf_counter()
        while done < total:
            n = min(wave, total - done)
            actors = [A.remote() for _ in range(n)]
            ray_tpu.get([a.ping.remote() for a in actors], timeout=600)
            for a in actors:
                ray_tpu.kill(a)
            done += n
        dt = time.perf_counter() - t0
        return {"actors": total, "wave": wave, "total_s": round(dt, 2),
                "actors_per_s": round(total / dt, 1)}
    finally:
        ray_tpu.shutdown()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--depths", default="10000,100000,1000000",
                   help="comma-separated queue depths for the task curve")
    p.add_argument("--pg-cycles", type=int, default=1000)
    p.add_argument("--actors", type=int, default=1000)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    from ray_tpu.core.config import Config
    cfg = Config()
    out = {
        "metric": "scale_envelope",
        "config": {
            "submit_inflight_limit": cfg.submit_inflight_limit,
            "submit_batching_enabled": cfg.submit_batching_enabled,
            "lease_queue_max_depth": cfg.lease_queue_max_depth,
            "gcs_table_shards": cfg.gcs_table_shards,
            "sched_metrics_enabled": cfg.sched_metrics_enabled,
        },
        "task_curve": [],
    }
    for d in [int(x) for x in args.depths.split(",") if x.strip()]:
        res = bench_depth(d)
        out["task_curve"].append(res)
        print(f"# depth {d}: {json.dumps(res)}", flush=True)
    if args.pg_cycles > 0:
        out["pg_cycles"] = bench_pg_cycles(args.pg_cycles)
        print(f"# pg: {json.dumps(out['pg_cycles'])}", flush=True)
    if args.actors > 0:
        out["actor_churn"] = bench_actor_churn(args.actors)
        print(f"# actors: {json.dumps(out['actor_churn'])}", flush=True)
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
